"""Pure detector functions over timeline sample windows.

Each detector takes an explicit window of ``(timestamp, value)`` points
for one series and returns either ``None`` (healthy) or a
JSON-serializable verdict dict. No clocks, no globals, no randomness:
given the same window the same verdict comes back bit-for-bit, which is
what lets flight-recorder replay recompute every ``timeline.finding``
and diff it against the recorded one (the ``record_forecast_outcome``
shadow-recompute idiom, applied to health verdicts).

Detector families (ROADMAP item 5's aging failure modes):

- **stall** — a counter a loop is contractually bumping (heartbeat
  observes, sampler ticks, plan cycles under load) goes flat for N
  consecutive samples while the loop claims to be alive.
- **leak** — a gauge or ``size.*`` series shows robust monotonic growth
  past a budget. The slope is a Theil–Sen fit (median of pairwise
  slopes), so a single reset or spike cannot fake or hide a leak.
- **regression** — the recent median of a sampled p95 series rises past
  ``ratio`` × its baseline-window median. Hysteresis (not re-firing
  while a finding is active, clearing only after quiet samples) lives
  in the engine, keeping these functions stateless.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

Point = Tuple[float, float]

STALL = "stall"
LEAK = "leak"
REGRESSION = "regression"

DEFAULT_STALL_WINDOWS = 5
DEFAULT_LEAK_BUDGET = 256.0
DEFAULT_LEAK_MIN_POINTS = 8
DEFAULT_LEAK_MONOTONIC_FRACTION = 0.9
DEFAULT_REGRESSION_RATIO = 1.5
DEFAULT_REGRESSION_MIN_POINTS = 8


def median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    mid = len(ordered) // 2
    if len(ordered) % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def theil_sen_slope(points: Sequence[Point]) -> float:
    """Median of all pairwise slopes — the robust trend estimator.
    Pairs with zero time delta are skipped; fewer than two usable pairs
    fit a slope of 0.0."""
    slopes: List[float] = []
    for i in range(len(points)):
        for j in range(i + 1, len(points)):
            dt = points[j][0] - points[i][0]
            if dt > 0:
                slopes.append((points[j][1] - points[i][1]) / dt)
    if not slopes:
        return 0.0
    return median(slopes)


def detect_stall(
    points: Sequence[Point],
    *,
    flat_windows: int = DEFAULT_STALL_WINDOWS,
) -> Optional[dict]:
    """Wedged-loop verdict: the counter did not move across the last
    ``flat_windows`` sample intervals, despite having moved before (a
    loop that never ran at all is a wiring problem, not a wedge — the
    caller's registration contract covers that)."""
    if len(points) < flat_windows + 1:
        return None
    tail = points[-(flat_windows + 1):]
    if any(b[1] > a[1] for a, b in zip(tail, tail[1:])):
        return None
    if tail[-1][1] <= 0:
        return None
    return {
        "detector": STALL,
        "flat_windows": flat_windows,
        "flat_since": tail[0][0],
        "last_value": tail[-1][1],
    }


def detect_leak(
    points: Sequence[Point],
    *,
    budget: float = DEFAULT_LEAK_BUDGET,
    min_points: int = DEFAULT_LEAK_MIN_POINTS,
    monotonic_fraction: float = DEFAULT_LEAK_MONOTONIC_FRACTION,
) -> Optional[dict]:
    """Monotonic-growth verdict: total growth across the window exceeds
    ``budget``, the Theil–Sen slope is positive, and at least
    ``monotonic_fraction`` of the consecutive steps are non-decreasing
    (a bounded ring filling up plateaus and stops matching; a churning
    cache dips and stops matching; a leak keeps climbing)."""
    if len(points) < min_points:
        return None
    growth = points[-1][1] - points[0][1]
    if growth <= budget:
        return None
    steps = [b[1] - a[1] for a, b in zip(points, points[1:])]
    rising = sum(1 for s in steps if s >= 0)
    if rising < monotonic_fraction * len(steps):
        return None
    slope = theil_sen_slope(points)
    if slope <= 0:
        return None
    return {
        "detector": LEAK,
        "growth": growth,
        "budget": budget,
        "slope_per_second": slope,
        "window_seconds": points[-1][0] - points[0][0],
    }


def detect_regression(
    points: Sequence[Point],
    *,
    baseline_points: int = DEFAULT_REGRESSION_MIN_POINTS,
    recent_points: int = DEFAULT_REGRESSION_MIN_POINTS,
    ratio: float = DEFAULT_REGRESSION_RATIO,
    abs_floor: float = 0.0,
) -> Optional[dict]:
    """Windowed-percentile regression: median of the last
    ``recent_points`` samples vs. the median of the first
    ``baseline_points`` samples of the series (the warm-up window is the
    baseline). ``abs_floor`` suppresses verdicts on microscopic
    baselines where the ratio is all noise."""
    if len(points) < baseline_points + recent_points:
        return None
    baseline = median([v for _, v in points[:baseline_points]])
    recent = median([v for _, v in points[-recent_points:]])
    if baseline <= 0:
        return None
    if recent <= baseline * ratio or recent - baseline <= abs_floor:
        return None
    return {
        "detector": REGRESSION,
        "baseline": baseline,
        "recent": recent,
        "ratio": recent / baseline,
        "threshold_ratio": ratio,
    }


def run_detector(
    detector: str,
    points: Sequence[Point],
    params: dict,
    *,
    normalized: bool = False,
) -> Optional[dict]:
    """Dispatch used by both the live engine and flight-recorder replay —
    one entry point guarantees both sides run the identical code path.

    ``normalized=True`` skips the float coercion for callers that already
    guarantee ``(float, float)`` tuples (the live engine's sample cache
    stores them that way); replay hands in JSON lists and must leave it
    False so verdict equality is about values, never container types.
    """
    fns = {STALL: detect_stall, LEAK: detect_leak, REGRESSION: detect_regression}
    if not normalized:
        points = [(float(t), float(v)) for t, v in points]
    return fns[detector](points, **params)
