"""SizeRegistry: one place every bounded-but-growable structure reports
its current cardinality.

Planner memos, verdict caches, the TraceStore ring, the FlightRecorder
ring, watch queues, grace reservations — anything whose unbounded growth
would be a leak — registers a zero-argument size callback under a stable
name. The TimelineStore samples the registry every tick into ``size.*``
series, which is what the leak detector watches.

Registration is replace-by-name: constructing a second TraceStore (tests
do this constantly) re-points the name at the live instance instead of
accumulating dead callbacks. Callbacks that raise are skipped for that
sample rather than killing the sampler.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict


class SizeRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: Dict[str, Callable[[], int]] = {}

    def register(self, name: str, size_fn: Callable[[], int]) -> None:
        """Register (or re-point) the size callback for ``name``."""
        with self._lock:
            self._sources[name] = size_fn

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def names(self):
        with self._lock:
            return sorted(self._sources)

    def sizes(self) -> Dict[str, float]:
        """Current size per registered name; erroring callbacks skipped."""
        with self._lock:
            sources = dict(self._sources)
        out: Dict[str, float] = {}
        for name in sorted(sources):
            try:
                out[name] = float(sources[name]())
            except Exception:
                continue
        return out


# Process-wide registry (the REGISTRY/TRACER/PROFILER analogue).
SIZES = SizeRegistry()
