"""WedgeWatchdog: the stall detector's loop registry.

Every controller loop that is contractually alive registers here and
beats once per iteration (or exposes an existing progress counter via
``counter_fn``). The TimelineStore samples each loop's counter into a
``loop.<name>`` series; loops registered ``periodic=True`` — ones whose
contract says they tick on a timer even when idle (capacity heartbeat,
forecaster resync, the timeline sampler itself) — are stall-checked
automatically, and a flat counter for N sample windows becomes a
wedged-loop verdict with the owning thread's profiler stacks attached.

Event-driven loops (the partitioner batch loop, watch-queue workers)
register ``periodic=False``: they still show up in ``loop.*`` series and
``/debug/timeline``, but idleness is legal for them, so they are only
stall-checked when a harness arms them explicitly.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

# NOTE: no top-level nos_tpu imports — this module sits below
# util.profiling/util.tracing in the import graph (tracing registers the
# trace ring with timeline.sizes at its bottom), so anything above must
# be imported function-locally.


class _Loop:
    __slots__ = ("name", "periodic", "thread_name", "counter_fn", "beats")

    def __init__(
        self,
        name: str,
        periodic: bool,
        thread_name: Optional[str],
        counter_fn: Optional[Callable[[], float]],
    ) -> None:
        self.name = name
        self.periodic = periodic
        self.thread_name = thread_name
        self.counter_fn = counter_fn
        self.beats = 0.0


class WedgeWatchdog:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loops: Dict[str, _Loop] = {}

    def register(
        self,
        name: str,
        *,
        periodic: bool = False,
        thread_name: Optional[str] = None,
        counter_fn: Optional[Callable[[], float]] = None,
    ) -> None:
        """Register (or re-register — tests rebuild components) a loop.
        ``periodic=True`` opts the loop into automatic stall checking."""
        with self._lock:
            self._loops[name] = _Loop(name, periodic, thread_name, counter_fn)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._loops.pop(name, None)

    def beat(self, name: str) -> None:
        """One loop iteration. Unregistered names auto-register as
        event-driven so a beat can never be dropped on the floor."""
        with self._lock:
            loop = self._loops.get(name)
            if loop is None:
                loop = _Loop(name, False, None, None)
                self._loops[name] = loop
            loop.beats += 1.0

    def counters(self) -> Dict[str, float]:
        """Current progress counter per registered loop (``counter_fn``
        when given, internal beats otherwise); erroring callbacks are
        skipped for this sample."""
        with self._lock:
            loops = list(self._loops.values())
        out: Dict[str, float] = {}
        for loop in loops:
            if loop.counter_fn is not None:
                try:
                    out[loop.name] = float(loop.counter_fn())
                except Exception:
                    continue
            else:
                out[loop.name] = loop.beats
        return out

    def periodic_loops(self) -> List[str]:
        with self._lock:
            return sorted(n for n, l in self._loops.items() if l.periodic)

    def thread_name(self, name: str) -> Optional[str]:
        with self._lock:
            loop = self._loops.get(name)
            return loop.thread_name if loop else None

    def stacks_for(self, name: str) -> List[str]:
        """The owning thread's collapsed profiler stacks — the payload a
        wedged-loop verdict ships so the operator sees *where* the loop
        is parked, not just that it stopped."""
        thread_name = self.thread_name(name)
        if not thread_name:
            return []
        from nos_tpu.util.profiling import PROFILER

        stacks = []
        for line in PROFILER.collapsed().splitlines():
            if line.startswith(f"{thread_name};"):
                stacks.append(line)
        return stacks

    def debug_payload(self) -> dict:
        with self._lock:
            loops = sorted(self._loops.values(), key=lambda l: l.name)
            return {
                "loops": [
                    {
                        "name": loop.name,
                        "periodic": loop.periodic,
                        "thread": loop.thread_name,
                        "external_counter": loop.counter_fn is not None,
                        "beats": loop.beats,
                    }
                    for loop in loops
                ]
            }


# Process-wide watchdog (the REGISTRY/TRACER/PROFILER analogue).
WATCHDOG = WedgeWatchdog()
