"""TimelineStore: the longitudinal health timeline.

Every observability layer in the suite answers "what is happening now";
this one answers "what has been drifting for the last 500 cycles". At a
configurable interval the store samples three collectors —

- the full metric registry snapshot (counters, gauges, histogram
  count/sum/percentiles, exactly the ``/debug/vars`` shape),
- process vitals (RSS from ``/proc/self/statm``, live thread count),
- the ``SizeRegistry`` (``size.*`` series) and ``WedgeWatchdog`` loop
  counters (``loop.*`` series)

— into a bounded, delta-encoded ring: each entry stores only the series
that changed since the previous sample, and evicted entries fold into a
base frame, so a steady-state process costs near-zero bytes per tick
while full per-sample values remain reconstructible for every retained
sample. The ring exports as JSONL, serves windowed rollups and
sparkline arrays on the bearer-gated ``/debug/timeline``, and feeds the
pure detectors in ``detectors.py``.

Detector verdicts are engine-stateful only for hysteresis (an active
finding does not re-fire every tick; it clears after ``clear_samples``
clean checks). Every NEW finding emits three ways at once:
``nos_tpu_timeline_findings_total{detector,series}``, a
``HealthDegraded`` Event through the EventRecorder, and a
``timeline.finding`` flight record carrying the exact detector inputs
(window + params) so replay recomputes the verdict bit-exactly.
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from nos_tpu.timeline import detectors
from nos_tpu.timeline.sizes import SIZES, SizeRegistry
from nos_tpu.timeline.watchdog import WATCHDOG, WedgeWatchdog
from nos_tpu.util import metrics

# NOTE: api constants and the profiler are imported function-locally:
# util.tracing registers its trace ring with timeline.sizes at module
# bottom, which initializes this package — anything that sits above
# tracing in the import graph (profiling, the api package via kube)
# would be re-entered half-built if imported here.

Point = Tuple[float, float]

_REMOVED = None  # delta sentinel: the series vanished this sample


class _RssReader:
    """Keeps ``/proc/self/statm`` open across samples — a fresh open()
    every interval is most of the cost of reading one integer."""

    def __init__(self) -> None:
        self._fh = None
        self._pagesize: Optional[int] = None

    def read(self) -> Optional[float]:
        try:
            if self._pagesize is None:
                import resource

                self._pagesize = resource.getpagesize()
            if self._fh is None:
                self._fh = open("/proc/self/statm", "rb")
            self._fh.seek(0)
            pages = int(self._fh.read().split()[1])
            return float(pages * self._pagesize)
        except Exception:
            if self._fh is not None:
                try:
                    self._fh.close()
                except Exception:
                    pass
                self._fh = None
            return None


_RSS = _RssReader()


def _rss_bytes() -> Optional[float]:
    return _RSS.read()


class DetectorPolicy:
    """Tuning budgets for the three detector families. Defaults are
    sized so a healthy soak (bounded rings filling, caches churning,
    counters ticking) stays clean; harnesses and teeth tests tighten
    them to put deliberate faults in range."""

    def __init__(
        self,
        *,
        stall_flat_windows: int = detectors.DEFAULT_STALL_WINDOWS,
        stall_series: Tuple[str, ...] = (),
        leak_budget: float = detectors.DEFAULT_LEAK_BUDGET,
        leak_budgets: Optional[Dict[str, float]] = None,
        leak_series: Tuple[str, ...] = (),
        leak_window: int = 64,
        leak_min_points: int = detectors.DEFAULT_LEAK_MIN_POINTS,
        leak_monotonic_fraction: float = detectors.DEFAULT_LEAK_MONOTONIC_FRACTION,
        regression_series: Tuple[str, ...] = (),
        regression_ratio: float = detectors.DEFAULT_REGRESSION_RATIO,
        regression_baseline_points: int = detectors.DEFAULT_REGRESSION_MIN_POINTS,
        regression_recent_points: int = detectors.DEFAULT_REGRESSION_MIN_POINTS,
        regression_abs_floor: float = 0.0,
        clear_samples: int = 3,
    ) -> None:
        self.stall_flat_windows = stall_flat_windows
        self.stall_series = tuple(stall_series)
        self.leak_budget = leak_budget
        self.leak_budgets = dict(leak_budgets or {})
        self.leak_series = tuple(leak_series)
        self.leak_window = leak_window
        self.leak_min_points = leak_min_points
        self.leak_monotonic_fraction = leak_monotonic_fraction
        self.regression_series = tuple(regression_series)
        self.regression_ratio = regression_ratio
        self.regression_baseline_points = regression_baseline_points
        self.regression_recent_points = regression_recent_points
        self.regression_abs_floor = regression_abs_floor
        self.clear_samples = clear_samples

    def stall_params(self) -> dict:
        return {"flat_windows": self.stall_flat_windows}

    def leak_params(self, series: str) -> dict:
        return {
            "budget": self.leak_budgets.get(series, self.leak_budget),
            "min_points": self.leak_min_points,
            "monotonic_fraction": self.leak_monotonic_fraction,
        }

    def regression_params(self) -> dict:
        return {
            "baseline_points": self.regression_baseline_points,
            "recent_points": self.regression_recent_points,
            "ratio": self.regression_ratio,
            "abs_floor": self.regression_abs_floor,
        }


class TimelineStore:
    MAX_FINDINGS = 256

    def __init__(
        self,
        *,
        capacity: int = 4096,
        interval_seconds: float = 5.0,
        clock: Callable[[], float] = time.time,
        policy: Optional[DetectorPolicy] = None,
        vitals: bool = True,
        metrics_fn: Optional[Callable[[], Dict[str, float]]] = None,
        sizes: Optional[SizeRegistry] = None,
        watchdog: Optional[WedgeWatchdog] = None,
        registry: Optional[metrics.MetricsRegistry] = None,
        recent_evict_frames: int = 8,
    ) -> None:
        self.capacity = capacity
        self.interval_seconds = interval_seconds
        self.clock = clock
        self.policy = policy or DetectorPolicy()
        self.vitals = vitals
        # Default mode rides an incremental registry cursor: each sample
        # folds (changed, removed) deltas into the carried value map, so
        # sampling cost is O(series touched this interval) — at 100k
        # nodes the full snapshot is ~400k series, of which a quiet
        # interval touches a few hundred. An explicit ``metrics_fn``
        # keeps the original full-snapshot diff mode (tests, synthetic
        # collectors, replay harnesses).
        if metrics_fn is None:
            self._registry = registry if registry is not None else metrics.REGISTRY
            self._cursor = self._registry.cursor()
            self.metrics_fn: Optional[Callable[[], Dict[str, float]]] = (
                self._registry.snapshot
            )
        else:
            self._registry = None
            self._cursor = None
            self.metrics_fn = metrics_fn
        self.sizes = SIZES if sizes is None else sizes
        self.watchdog = WATCHDOG if watchdog is None else watchdog
        self._lock = threading.Lock()
        self._entries: List[dict] = []
        self._base: Dict[str, float] = {}
        self._last: Dict[str, float] = {}
        # Detector fast path: the last few points of every WATCHED series
        # (stall/leak/regression targets — not the whole registry), kept
        # incrementally so a detector pass never replays the delta ring
        # (which is O(ring length) per reconstruction). Sized to the
        # largest window any configured detector looks at. Series absent
        # ``recent_evict_frames`` consecutive samples are evicted, so
        # node/pod churn cannot grow the cache with tombstone deques;
        # the grace window keeps history across a one-sample flap.
        self._recent_len = max(
            self.policy.leak_window,
            self.policy.stall_flat_windows + 1,
            self.policy.regression_baseline_points
            + self.policy.regression_recent_points,
        )
        self._recent: Dict[str, Deque[Point]] = {}
        self.recent_evict_frames = max(1, recent_evict_frames)
        self._recent_absent: Dict[str, int] = {}
        # Aux collector keys (size./loop./process.) seen last sample —
        # cursor mode needs them to detect aux series removal, since the
        # cursor only covers the registry.
        self._aux_keys: set = set()
        self._samples = 0
        # The cache itself is leak-detector-visible: a growing
        # recent_series under node churn is exactly the tombstone leak
        # this store must not have.
        self.sizes.register("timeline.recent_series", lambda: len(self._recent))
        self._findings: List[dict] = []
        self._active: Dict[Tuple[str, str], dict] = {}
        self._flight = None
        self.recorder = None
        self._event_obj = None
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()

    # -- emission wiring --------------------------------------------------

    def attach(self, *, flight=None, recorder=None, event_obj=None) -> None:
        """Wire finding emission: ``flight`` gets ``timeline.finding``
        records, ``recorder`` (an EventRecorder) gets ``HealthDegraded``
        Events against ``event_obj``."""
        self._flight = flight
        self.recorder = recorder
        self._event_obj = event_obj

    # -- sampling ---------------------------------------------------------

    def _collect_aux(self) -> Dict[str, float]:
        """The non-registry collectors (sizes, watchdog loops, vitals) —
        cheap, bounded families always sampled in full."""
        values: Dict[str, float] = {}
        for name, size in self.sizes.sizes().items():
            values[f"size.{name}"] = size
        for name, count in self.watchdog.counters().items():
            values[f"loop.{name}"] = count
        if self.vitals:
            rss = _rss_bytes()
            if rss is not None:
                values["process.rss_bytes"] = rss
            values["process.threads"] = float(threading.active_count())
        return values

    def collect(self) -> Dict[str, float]:
        """One full sample across all collectors (no ring mutation)."""
        values: Dict[str, float] = {}
        if self.metrics_fn is not None:
            values.update(self.metrics_fn())
        values.update(self._collect_aux())
        return values

    def _watched_names(self) -> set:
        """Series the detector cache must hold: stall targets, explicit
        leak/regression series — ``size.*`` keys are matched by prefix
        at insertion (the sized set is dynamic)."""
        watched = {f"loop.{name}" for name in self.watchdog.periodic_loops()}
        watched.update(self.policy.stall_series)
        watched.update(self.policy.leak_series)
        watched.update(self.policy.regression_series)
        return watched

    def sample_once(self, now: Optional[float] = None) -> Dict[str, float]:
        """Append one delta-encoded sample to the ring."""
        started = time.perf_counter()
        if now is None:
            now = self.clock()
        if self._cursor is None:
            values = self.collect()
            changed: Optional[Dict[str, float]] = None
            removed: List[str] = []
        else:
            changed, removed = self._cursor.collect()
            aux = self._collect_aux()
        watched = self._watched_names()
        with self._lock:
            if self._cursor is None:
                delta: Dict[str, Optional[float]] = {
                    k: v for k, v in values.items() if self._last.get(k) != v
                }
                for gone in self._last:
                    if gone not in values:
                        delta[gone] = _REMOVED
            else:
                # Fold the cursor delta (and the fully-sampled aux
                # families) into the carried value map — O(touched).
                values = dict(self._last)
                delta = {}
                for key in removed:
                    if key in values:
                        del values[key]
                        delta[key] = _REMOVED
                for key, value in changed.items():
                    if values.get(key) != value:
                        values[key] = value
                        delta[key] = value
                for key in self._aux_keys:
                    if key not in aux and key in values:
                        del values[key]
                        delta[key] = _REMOVED
                for key, value in aux.items():
                    if values.get(key) != value:
                        values[key] = value
                        delta[key] = value
                self._aux_keys = set(aux)
            for name, value in values.items():
                if name not in watched and not name.startswith("size."):
                    continue
                window = self._recent.get(name)
                if window is None:
                    window = self._recent[name] = collections.deque(
                        maxlen=self._recent_len
                    )
                # Floats at insertion so detector windows are already
                # normalized — the recorded window then round-trips
                # through JSON bit-identically for replay recompute.
                window.append((float(now), float(value)))
                self._recent_absent.pop(name, None)
            for name in list(self._recent):
                if name not in values:
                    absent = self._recent_absent.get(name, 0) + 1
                    if absent >= self.recent_evict_frames:
                        self._recent.pop(name, None)
                        self._recent_absent.pop(name, None)
                    else:
                        self._recent_absent[name] = absent
            self._entries.append({"t": now, "d": delta})
            while len(self._entries) > self.capacity:
                evicted = self._entries.pop(0)
                for key, value in evicted["d"].items():
                    if value is _REMOVED:
                        self._base.pop(key, None)
                    else:
                        self._base[key] = value
            self._last = values
            self._samples += 1
        metrics.TIMELINE_SAMPLES.inc()
        metrics.TIMELINE_SERIES.set(len(values))
        metrics.TIMELINE_SAMPLE_DURATION.observe(time.perf_counter() - started)
        return values

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """Sample then detect — the unit of work one sampler interval
        (or one virtual-clock harness step) performs."""
        if now is None:
            now = self.clock()
        self.sample_once(now)
        return self.check(now)

    # -- ring reads -------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._last)

    def series(self, name: str, window_seconds: Optional[float] = None) -> List[Point]:
        """Per-sample points for one series, values carried forward
        through samples where it did not change."""
        with self._lock:
            entries = list(self._entries)
            current = self._base.get(name)
        points: List[Point] = []
        for entry in entries:
            if name in entry["d"]:
                current = entry["d"][name]
            if current is not None:
                points.append((entry["t"], current))
        if window_seconds is not None and points:
            horizon = points[-1][0] - window_seconds
            points = [p for p in points if p[0] >= horizon]
        return points

    def series_many(self, names: List[str]) -> Dict[str, List[Point]]:
        """Carry-forward points for many series off ONE ring scan.
        ``series()`` is O(ring) per call, so a detector pass over N
        watched series would pay N full scans per tick; this keeps the
        per-tick sampling cost flat as series accumulate."""
        with self._lock:
            entries = list(self._entries)
            current: Dict[str, Optional[float]] = {
                name: self._base.get(name) for name in names
            }
        out: Dict[str, List[Point]] = {name: [] for name in names}
        for entry in entries:
            delta = entry["d"]
            t = entry["t"]
            for name in names:
                if name in delta:
                    current[name] = delta[name]
                value = current[name]
                if value is not None:
                    out[name].append((t, value))
        return out

    def iter_jsonl(self):
        """Yield the ring frame-by-frame (header dict, then one delta
        dict per retained sample) — the chunked ``?format=jsonl`` debug
        path encodes each frame as it goes, never holding the whole
        export. The ring is snapshotted under the lock once; entries are
        append-only dicts, so yielding outside the lock is safe."""
        with self._lock:
            header = {
                "kind": "timeline.base",
                "base": dict(sorted(self._base.items())),
                "samples": self._samples,
            }
            entries = list(self._entries)
        yield header
        for entry in entries:
            yield {"t": entry["t"], "d": dict(sorted(entry["d"].items()))}

    def to_jsonl(self) -> str:
        """The ring as JSONL: a header frame with the folded base, then
        one delta frame per retained sample."""
        return (
            "\n".join(json.dumps(frame, sort_keys=True) for frame in self.iter_jsonl())
            + "\n"
        )

    def export(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write(self.to_jsonl())

    # -- detectors --------------------------------------------------------

    def _stall_targets(self) -> List[str]:
        targets = [f"loop.{name}" for name in self.watchdog.periodic_loops()]
        targets.extend(self.policy.stall_series)
        return targets

    def _leak_targets(self) -> List[str]:
        with self._lock:
            sized = [n for n in sorted(self._last) if n.startswith("size.")]
        sized.extend(self.policy.leak_series)
        return sized

    def _detector_windows(self):
        """Yield ``(detector, series, window, params)`` for every
        configured detector pass. Windows come from the incremental
        per-series cache, not a ring replay — the detector pass stays
        O(watched series), flat in both ring depth and total series
        count. Regression baselines are therefore rolling (oldest
        retained points), which is also what hysteresis wants: a one-off
        warm-up blip ages out."""
        stall_targets = self._stall_targets()
        leak_targets = self._leak_targets()
        with self._lock:
            history = {
                name: list(self._recent.get(name, ()))
                for name in set(stall_targets)
                | set(leak_targets)
                | set(self.policy.regression_series)
            }
        stall_params = self.policy.stall_params()
        for name in stall_targets:
            points = history[name][-(self.policy.stall_flat_windows + 1):]
            yield detectors.STALL, name, points, stall_params
        for name in leak_targets:
            points = history[name][-self.policy.leak_window:]
            yield detectors.LEAK, name, points, self.policy.leak_params(name)
        regression_params = self.policy.regression_params()
        for name in self.policy.regression_series:
            yield detectors.REGRESSION, name, history[name], regression_params

    def evaluate(self) -> List[dict]:
        """Run every configured detector over its current window and
        return the raw evaluations (verdict or None each) — the pure
        core ``check()`` wraps with hysteresis and emission."""
        return [
            {
                "detector": detector,
                "series": name,
                "window": points,
                "params": params,
                "verdict": detectors.run_detector(
                    detector, points, params, normalized=True
                )
                if points
                else None,
            }
            for detector, name, points, params in self._detector_windows()
        ]

    def check(self, now: Optional[float] = None) -> List[dict]:
        """Detect over the current ring; returns only NEW findings (an
        active finding refreshes silently until it clears)."""
        if now is None:
            now = self.clock()
        new_findings: List[dict] = []
        seen: Dict[Tuple[str, str], bool] = {}
        for detector, name, points, params in self._detector_windows():
            verdict = (
                detectors.run_detector(detector, points, params, normalized=True)
                if points
                else None
            )
            key = (detector, name)
            seen[key] = verdict is not None
            active = self._active.get(key)
            if verdict is not None:
                if active is None:
                    finding = {
                        "t": now,
                        "detector": detector,
                        "series": name,
                        "verdict": verdict,
                        "window": points,
                        "params": params,
                    }
                    if detector == detectors.STALL:
                        loop = name
                        if loop.startswith("loop."):
                            loop = loop[len("loop."):]
                        finding["stacks"] = self.watchdog.stacks_for(loop)
                    self._active[key] = {"verdict": verdict, "clean": 0}
                    self._record_finding(finding)
                    new_findings.append(finding)
                else:
                    active["verdict"] = verdict
                    active["clean"] = 0
        for key in list(self._active):
            if seen.get(key):
                continue
            active = self._active[key]
            active["clean"] += 1
            if active["clean"] >= self.policy.clear_samples:
                del self._active[key]
        return new_findings

    def _record_finding(self, finding: dict) -> None:
        with self._lock:
            self._findings.append(finding)
            if len(self._findings) > self.MAX_FINDINGS:
                self._findings.pop(0)
        metrics.TIMELINE_FINDINGS.labels(
            detector=finding["detector"], series=finding["series"]
        ).inc()
        if self._flight is not None:
            self._flight.record_timeline_finding(
                t=finding["t"],
                detector=finding["detector"],
                series=finding["series"],
                window=[[t, v] for t, v in finding["window"]],
                params=finding["params"],
                verdict=finding["verdict"],
                stacks=finding.get("stacks", []),
            )
        if self.recorder is not None and self._event_obj is not None:
            from nos_tpu.api.v1alpha1 import constants

            message = (
                f"{finding['detector']} finding on {finding['series']}: "
                f"{json.dumps(finding['verdict'], sort_keys=True)}"
            )
            self.recorder.record(
                self._event_obj,
                constants.EVENT_REASON_HEALTH_DEGRADED,
                message,
                type="Warning",
            )

    def findings(self, detector: Optional[str] = None) -> List[dict]:
        with self._lock:
            found = list(self._findings)
        if detector is not None:
            found = [f for f in found if f["detector"] == detector]
        return found

    def findings_payload(self) -> dict:
        """JSON-stable findings summary (windows and stacks elided) —
        what the soak harness diffs across runs."""
        return {
            "findings": [
                {
                    "t": f["t"],
                    "detector": f["detector"],
                    "series": f["series"],
                    "verdict": f["verdict"],
                }
                for f in self.findings()
            ]
        }

    # -- rollups / debug --------------------------------------------------

    def rollups(self, window_seconds: Optional[float] = None) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for name in self.names():
            points = self.series(name, window_seconds)
            if not points:
                continue
            values = [v for _, v in points]
            out[name] = {
                "first": values[0],
                "last": values[-1],
                "min": min(values),
                "max": max(values),
                "delta": values[-1] - values[0],
                "points": len(values),
            }
        return out

    def sparkline(
        self,
        name: str,
        points: int = 32,
        window_seconds: Optional[float] = None,
    ) -> List[float]:
        """Evenly-resampled recent values — what the debug page plots."""
        series = self.series(name, window_seconds)
        if not series:
            return []
        if len(series) <= points:
            return [v for _, v in series]
        step = (len(series) - 1) / (points - 1)
        return [series[int(round(i * step))][1] for i in range(points)]

    def debug_payload(
        self,
        window_seconds: Optional[float] = None,
        spark_points: int = 32,
        limit: int = 0,
        cursor: str = "",
    ) -> dict:
        """``limit``/``cursor`` page the per-series sections (rollups +
        sparklines) by series name; the scalar summary always covers the
        whole ring. Defaults reproduce the full pre-paging document."""
        from nos_tpu.obsplane.streaming import paginate

        names = self.names()
        page_names, next_cursor = paginate(names, limit, cursor)
        rollups: Dict[str, dict] = {}
        for name in page_names:
            points = self.series(name, window_seconds)
            if not points:
                continue
            values = [v for _, v in points]
            rollups[name] = {
                "first": values[0],
                "last": values[-1],
                "min": min(values),
                "max": max(values),
                "delta": values[-1] - values[0],
                "points": len(values),
            }
        return {
            "samples": self.samples,
            "retained": len(self),
            "capacity": self.capacity,
            "interval_seconds": self.interval_seconds,
            "series_count": len(rollups),
            "window_seconds": window_seconds,
            "watchdog": self.watchdog.debug_payload(),
            "active_findings": sorted(
                f"{d}:{s}" for d, s in self._active
            ),
            "findings": self.findings_payload()["findings"],
            "rollups": rollups,
            "sparklines": {
                name: self.sparkline(name, spark_points, window_seconds)
                for name in rollups
            },
            "page": {
                "limit": limit,
                "cursor": cursor,
                "next_cursor": next_cursor,
                "total_series": len(names),
            },
        }

    # -- sampler thread ---------------------------------------------------

    def start(self) -> None:
        """Background sampler: one ``tick()`` per interval on a daemon
        thread registered with the profiler and the watchdog (a wedged
        sampler cannot report itself — its silence shows up as a frozen
        ``samples`` count on /debug/timeline instead)."""
        if self._thread is not None:
            return
        self._stop_event.clear()
        self.watchdog.register(
            "timeline-sampler", periodic=True, thread_name="timeline-sampler"
        )
        self._thread = threading.Thread(
            target=self._loop, name="timeline-sampler", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        from nos_tpu.util.profiling import PROFILER

        PROFILER.register_thread(name="timeline-sampler")
        try:
            while not self._stop_event.wait(self.interval_seconds):
                self.watchdog.beat("timeline-sampler")
                self.tick()
        finally:
            PROFILER.unregister_thread()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop_event.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self.watchdog.unregister("timeline-sampler")

    def close(self) -> None:
        """Detach the registry cursor (idempotent). A closed store falls
        back to full-snapshot sampling if sampled again — harnesses that
        build many short-lived stores against the process registry call
        this so abandoned cursors stop accumulating deltas."""
        if self._cursor is not None:
            self._cursor.close()
            self._cursor = None
