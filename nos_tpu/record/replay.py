"""ReplaySession: deterministic offline re-execution of a recorded log.

The recorded deltas ARE the cluster history: replay rebuilds a fresh
KubeStore by applying them in revision order (preserving the recorded
resource versions), pausing at each decision record's watermark to
re-run the decision against exactly the state it saw live. Decisions
replay in sequence order through ONE scheduler and ONE planner instance,
so order-dependent in-memory state (the assume cache, gang formation,
plan caches) accumulates the way it did live.

Drift is compared per decision:

- ``scheduler.cycle`` — (decision, node, sorted bound pairs, sorted
  victims) must match the record.
- ``planner.plan``    — the replayed desired PartitioningState must be
  equal (unordered, empty-board-insensitive) to the recorded one. The
  recorded ``pending_ages`` feed the planner so the aging-dependent
  candidate sort reproduces without the live process's clock history.

After each replayed plan the invariant auditor runs exhaustively —
replay is where "sampled in live mode" becomes "every entry, every
plan".

Known non-replayable inputs (reported as skips, not drift): decisions
whose pod no longer resolves at the watermark, and decision kinds the
replayer treats as informational (quota reconciles, actuations — both
are deterministic functions of state already covered by the deltas).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from nos_tpu.record.audit import InvariantAuditor


@dataclass
class ReplayReport:
    cycles: int = 0
    plans: int = 0
    capacity_observes: int = 0
    forecast_cycles: int = 0
    forecast_outcomes: int = 0
    timeline_findings: int = 0
    drifts: List[dict] = field(default_factory=list)
    violations: List[dict] = field(default_factory=list)
    skips: List[dict] = field(default_factory=list)

    def ok(self) -> bool:
        return not self.drifts and not self.violations

    def render(self) -> str:
        lines = [
            f"replayed {self.cycles} scheduler cycle(s), {self.plans} plan(s), "
            f"{self.capacity_observes} capacity observe(s), "
            f"{self.forecast_outcomes} forecast outcome(s), "
            f"{self.timeline_findings} timeline finding(s): "
            f"{len(self.drifts)} drift(s), {len(self.violations)} audit "
            f"violation(s), {len(self.skips)} skip(s)"
        ]
        for drift in self.drifts:
            lines.append(f"  DRIFT seq={drift.get('seq')}: {drift.get('detail')}")
        for violation in self.violations:
            lines.append(
                f"  AUDIT {violation.get('check')}: {violation.get('detail')}"
            )
        for skip in self.skips:
            lines.append(f"  skip seq={skip.get('seq')}: {skip.get('detail')}")
        return "\n".join(lines)


class ReplaySession:
    def __init__(self, records: List[dict]) -> None:
        from nos_tpu.cmd.partitioner import build_sim_framework, register_indexers
        from nos_tpu.kube.store import KubeStore
        from nos_tpu.partitioning.core import Planner
        from nos_tpu.scheduler.scheduler import Scheduler, new_framework

        self.records = records
        self.meta = next(
            (r for r in records if r.get("kind") == "session.start"), {}
        )
        self.store = KubeStore()
        register_indexers(self.store)
        # Deltas ordered by the revision the store stamped, not arrival:
        # the recorder's drain thread can observe writes out of order
        # across threads, but revisions are the store's own total order.
        self.deltas = sorted(
            (r for r in records if r.get("kind") == "delta"),
            key=lambda r: (r["revision"], r["seq"]),
        )
        self._delta_index = 0
        # Decisions replay in WATERMARK order, not record order: a plan's
        # record is emitted at plan END (seq after any scheduler cycles
        # that ran concurrently) but its watermark was captured at plan
        # START. Seq order would fast-forward the store past the plan's
        # watermark — feeding it its own actuation writes — because the
        # delta cursor only moves forward. Each stream is serialized
        # live, so per-stream watermark order equals execution order and
        # in-memory state still accumulates correctly.
        self.decisions = sorted(
            (
                r
                for r in records
                if r.get("kind")
                in ("scheduler.cycle", "planner.plan", "capacity.observe")
            ),
            key=lambda r: (r.get("revision", 0), r["seq"]),
        )
        # Forecast records replay off the store cursor: outcomes are a
        # pure function of the recorded joins (fed through a shadow
        # CalibrationTracker in seq order), cycles are informational.
        self.forecast_records = sorted(
            (
                r
                for r in records
                if r.get("kind") in ("forecast.cycle", "forecast.outcome")
            ),
            key=lambda r: r["seq"],
        )
        # Timeline findings carry their own detector inputs (window +
        # params), so they replay standalone: re-run the pure detector
        # over the recorded window and demand the identical verdict.
        self.timeline_records = sorted(
            (r for r in records if r.get("kind") == "timeline.finding"),
            key=lambda r: r["seq"],
        )
        framework, capacity, gang = new_framework(
            self.store,
            gang_timeout_seconds=self.meta.get("gang_timeout_seconds", 30.0),
        )
        self.scheduler = Scheduler(
            self.store,
            framework,
            capacity,
            gang,
            scheduler_name=self.meta.get("scheduler_name", ""),
        )
        aging = self.meta.get("aging_chips_per_second", 1.0)
        # One planner per partitioner kind (tpu / sharing), same plugin set
        # as the live controllers (build_sim_framework).
        self._planners = {
            kind: Planner(
                build_sim_framework(self.store), aging_chips_per_second=aging
            )
            for kind in ("tpu", "sharing")
        }
        self.auditor = InvariantAuditor(sample_rate=1.0)
        # Shadow capacity ledger: watches the replay store (constructed
        # BEFORE any delta applies, so its watch sees every event), fed
        # the recorded observe timestamps — its integrals must land
        # bit-exactly on the recorded totals. No metrics, no recorder:
        # replay must not pollute gauges or re-record.
        from nos_tpu.capacity import CapacityLedger

        self.capacity_ledger = CapacityLedger(
            self.store, flight_recorder=None, metrics=False
        )

    # ----------------------------------------------------------- state

    def _apply_deltas_up_to(self, revision: int) -> None:
        from nos_tpu.kube import serde

        while self._delta_index < len(self.deltas):
            delta = self.deltas[self._delta_index]
            if delta["revision"] > revision:
                return
            self.store.apply_event(delta["type"], serde.from_wire(delta["object"]))
            self._delta_index += 1

    def _snapshot_taker(self, kind: str):
        if kind == "sharing":
            from nos_tpu.partitioning.sharing import SharingSnapshotTaker

            return SharingSnapshotTaker()
        from nos_tpu.partitioning.tpu import TpuSnapshotTaker

        return TpuSnapshotTaker()

    # ------------------------------------------------------------- run

    def run(self) -> ReplayReport:
        report = ReplayReport()
        for record in self.decisions:
            self._apply_deltas_up_to(record.get("revision", 0))
            if record["kind"] == "scheduler.cycle":
                self._replay_cycle(record, report)
            elif record["kind"] == "capacity.observe":
                self._replay_capacity(record, report)
            else:
                self._replay_plan(record, report)
        self._replay_forecasts(report)
        self._replay_timeline(report)
        return report

    def _replay_timeline(self, report: ReplayReport) -> None:
        """Health-verdict audit: every ``timeline.finding`` recorded the
        exact window and parameters its detector saw, and the detectors
        are pure functions of those inputs — re-running one must land on
        the recorded verdict bit-for-bit (floats JSON-round-trip
        exactly). A mismatch means the detector code drifted from what
        produced the recording, or the recording was tampered with."""
        from nos_tpu.timeline.detectors import run_detector

        for record in self.timeline_records:
            report.timeline_findings += 1
            got = run_detector(
                record["detector"],
                record.get("window", []),
                record.get("params", {}),
            )
            want = record.get("verdict")
            if got != want:
                report.drifts.append(
                    {
                        "seq": record["seq"],
                        "kind": "timeline.finding",
                        "series": record.get("series", ""),
                        "detail": (
                            f"recorded verdict {want} but replay "
                            f"recomputed {got}"
                        ),
                    }
                )

    def _replay_forecasts(self, report: ReplayReport) -> None:
        """Forecast-accuracy audit: re-feed the recorded outcome joins
        through a fresh CalibrationTracker and demand each record's
        running calibration payload bit-for-bit. The tracker is a pure
        function of its add() history (nearest-rank percentiles, plain
        float arithmetic), so any mismatch means the live join sequence
        diverged from what was recorded."""
        from nos_tpu.forecast.accuracy import CalibrationTracker

        shadow = CalibrationTracker()
        for record in self.forecast_records:
            if record["kind"] == "forecast.cycle":
                report.forecast_cycles += 1
                continue
            report.forecast_outcomes += 1
            shadow.add(
                record.get("eta_seconds"),
                record.get("actual_seconds", 0.0),
                record.get("wait_seconds", 0.0),
                stage=record.get("stage", ""),
            )
            got = shadow.payload()
            want = record.get("calibration", {})
            if got != want:
                report.drifts.append(
                    {
                        "seq": record["seq"],
                        "kind": "forecast.outcome",
                        "gang": record.get("gang", ""),
                        "detail": (
                            f"recorded calibration {want} but replay "
                            f"recomputed {got}"
                        ),
                    }
                )

    def _replay_cycle(self, record: dict, report: ReplayReport) -> None:
        namespace, _, name = record["pod"].partition("/")
        pod = self.store.try_get("Pod", name, namespace)
        if pod is None:
            report.skips.append(
                {
                    "seq": record["seq"],
                    "detail": f"pod {record['pod']} absent at revision "
                    f"{record.get('revision')}",
                }
            )
            return
        report.cycles += 1
        outcome = self.scheduler.decide(pod)
        if record.get("settled", True):
            self.scheduler.settle(outcome)
        # settled=False: the live cycle's store writes failed (conflict,
        # apiserver outage) — the bind never happened, so the replay store
        # must not apply it either; the retry cycle's record covers the
        # eventual outcome. The decision comparison below still holds:
        # decide() is a function of observed state, which failed writes
        # don't change.
        got = {
            "decision": outcome.decision,
            "node": outcome.node,
            "bound": sorted(
                [p.namespaced_name, n] for p, n in outcome.to_bind
            ),
            "victims": sorted(outcome.victims),
        }
        want = {
            "decision": record["decision"],
            "node": record.get("node", ""),
            "bound": sorted(list(pair) for pair in record.get("bound", [])),
            "victims": sorted(record.get("victims", [])),
        }
        if got != want:
            report.drifts.append(
                {
                    "seq": record["seq"],
                    "kind": "scheduler.cycle",
                    "pod": record["pod"],
                    "detail": f"recorded {want} but replay decided {got}",
                }
            )

    def _replay_plan(self, record: dict, report: ReplayReport) -> None:
        from nos_tpu.partitioning.core.partition_state import (
            partitioning_state_equal,
            partitioning_state_from_dict,
            partitioning_state_to_dict,
        )
        from nos_tpu.partitioning.core.state import ClusterState

        kind = record.get("partitioner_kind", "tpu")
        planner = self._planners.get(kind)
        if planner is None:
            report.skips.append(
                {
                    "seq": record["seq"],
                    "detail": f"unknown partitioner kind {kind!r}",
                }
            )
            return
        pending = []
        for key in record.get("pending", []):
            namespace, _, name = key.partition("/")
            pod = self.store.try_get("Pod", name, namespace)
            if pod is not None:
                pending.append(pod)
        report.plans += 1
        snapshot = self._snapshot_taker(kind).take_snapshot(
            ClusterState(), store=self.store
        )
        desired = planner.plan(
            snapshot, pending, pending_ages=record.get("pending_ages", {})
        )
        recorded = partitioning_state_from_dict(record.get("desired", {}))
        if not partitioning_state_equal(desired, recorded):
            report.drifts.append(
                {
                    "seq": record["seq"],
                    "kind": "planner.plan",
                    "plan_id": record.get("plan_id", ""),
                    "detail": (
                        f"recorded desired {record.get('desired')} but replay "
                        f"planned {partitioning_state_to_dict(desired)}"
                    ),
                }
            )
        violations = self.auditor.audit_plan(
            planner, snapshot, exhaustive=True, revision=record.get("revision", 0)
        )
        report.violations.extend(v.to_dict() for v in violations)

    def _replay_capacity(self, record: dict, report: ReplayReport) -> None:
        """Re-integrate the shadow ledger up to the recorded timestamp and
        demand the recorded totals bit-for-bit. Chip-second integrals are
        sums of float products in deterministic (sorted) order over state
        derived purely from the deltas, and JSON round-trips IEEE doubles
        exactly — so equality here is ==, not almost-equal. Any mismatch
        means the incremental bookkeeping diverged from the recorded run."""
        report.capacity_observes += 1
        self.capacity_ledger.observe(
            record["now"], reason=record.get("reason", ""), record=False
        )
        got = self.capacity_ledger.totals()
        want = record.get("totals", {})
        if got != want:
            report.drifts.append(
                {
                    "seq": record["seq"],
                    "kind": "capacity.observe",
                    "detail": f"recorded totals {want} but replay integrated {got}",
                }
            )
        # The live auditor samples self_check only when the store is quiet;
        # replay is single-threaded, so every observe gets the exhaustive
        # incremental-vs-from-scratch comparison.
        for diff in self.capacity_ledger.self_check(self.store):
            report.violations.append(
                {"check": "capacity_ledger", "subject": "ledger", "detail": diff}
            )


def replay_file(path: str) -> ReplayReport:
    """Convenience wrapper: load a JSONL export and replay it."""
    from nos_tpu.record.recorder import load_jsonl

    return ReplaySession(load_jsonl(path)).run()


def drift_exit_code(report: Optional[ReplayReport]) -> int:
    return 0 if report is not None and report.ok() else 1
