"""Flight recorder: decision-log capture, offline replay, cache auditing.

The control plane's hot decisions (bind this pod, carve that node, flip
that quota label) flow through layered incremental state — CoW snapshots,
the verdict cache, incremental lacking totals, the futility memo — whose
silent drift would corrupt decisions without failing a test. This package
closes that loop:

- ``FlightRecorder`` (recorder.py): per control cycle (scheduler cycle,
  ``planner.plan()``, quota reconcile, actuation) captures a compact
  record — input deltas keyed by store revision, decision outputs,
  clock stamps, trace-id/Diagnosis links — into a bounded ring with
  JSONL export, served at ``/debug/record``.
- ``ReplaySession`` (replay.py): reconstructs cluster state from the
  recorded deltas and deterministically re-runs the scheduler and
  planner over each cycle, diffing decisions against the recorded ones.
- ``InvariantAuditor`` (audit.py): named checks that shadow-recompute
  ground truth for each incremental structure and compare (sampled in
  live mode, exhaustive in replay).
"""
from nos_tpu.record.audit import AuditViolation, InvariantAuditor
from nos_tpu.record.recorder import FlightRecorder, load_jsonl
from nos_tpu.record.replay import ReplayReport, ReplaySession

__all__ = [
    "AuditViolation",
    "FlightRecorder",
    "InvariantAuditor",
    "ReplayReport",
    "ReplaySession",
    "load_jsonl",
]
