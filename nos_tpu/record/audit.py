"""InvariantAuditor: shadow-recompute ground truth for the incremental
planning structures and compare.

PR 1/3 made the planner fast by making it incremental: the CoW snapshot
maintains the free pool by delta, the verdict cache memoizes plugin
conjunctions per (pod-signature, node, version), SliceTracker keeps
lacking totals current by subtraction, and the carve-futility memo skips
whole fork+carve trials. Each structure has an exact ground truth it
claims to equal — `_compute_free_pool`, a fresh plugin run, a full
re-sum, a real carve attempt. The auditor recomputes those truths and
compares, so silent cache drift becomes a counted, evented, traceable
violation instead of a corrupted decision.

Named checks:

- ``verdict_cache``   cached verdicts vs. a fresh uncached cacheable-
                      plugin run (entries at the node's current version)
- ``lacking_totals``  SliceTracker's incremental per-accelerator totals
                      vs. a full re-sum over its lacking map
- ``free_pool``       the snapshot's incremental free pool vs.
                      ``_compute_free_pool()``
- ``mutation_clock``  node versions never exceed ``state_version``, and
                      no two live nodes share a nonzero tick
- ``carve_futility``  memoized "carve is a no-op" entries vs. an actual
                      forked carve attempt (reverted)
- ``incremental_plan`` a warm-started (incremental-mode) plan's desired
                      PartitioningState and unserved reasons vs. a
                      from-scratch shadow replan of the same pending set
                      on a fresh clone of the base snapshot (runs only
                      when the audited plan actually took the incremental
                      path and the controller passed its inputs along)
- ``capacity_ledger`` the CapacityLedger's incrementally-maintained
                      instantaneous state (per-node chips/flags/
                      fragmentation, bound/pending pods, quota posture)
                      vs. a from-scratch recomputation off the store
                      (runs only when the controller passed its ledger
                      along; skips silently while concurrent writers
                      hold the store past the ledger's watermark)

Live mode samples (deterministic counter stride, config-controlled) and
caps per-check work; replay audits exhaustively. Replay is ALSO the
exhaustive oracle for incremental planning as a whole: live records the
incrementally-computed desired state, while replayed planners always run
the full from-scratch path — the replay driver's desired-state diff is
therefore an end-to-end incremental-vs-from-scratch comparison over every
recorded plan, with the live shadow check naturally idle there.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional

from nos_tpu.util import metrics
from nos_tpu.util import resources as res

CHECKS = (
    "verdict_cache",
    "lacking_totals",
    "free_pool",
    "mutation_clock",
    "carve_futility",
    "incremental_plan",
    "capacity_ledger",
)


def _nonzero(pool: Dict[str, int]) -> Dict[str, int]:
    """Zero entries are representation noise (a drained counter left at 0
    vs. popped), not drift."""
    return {k: v for k, v in pool.items() if v}


@dataclass
class AuditViolation:
    check: str
    subject: str  # node name, accelerator, or cache-key description
    detail: str
    node: str = ""  # set when node-scoped, for Event targeting

    def to_dict(self) -> dict:
        return {
            "check": self.check,
            "subject": self.subject,
            "detail": self.detail,
            "node": self.node,
        }


class InvariantAuditor:
    def __init__(
        self,
        sample_rate: float = 0.0,
        recorder=None,
        flight_recorder=None,
        max_entries_per_check: int = 8,
    ) -> None:
        # Fraction of plans audited in live mode. Sampling is a
        # deterministic counter stride, not a coin flip: replayed sessions
        # must audit the same plans the live run did.
        self.sample_rate = sample_rate
        self.recorder = recorder  # kube EventRecorder for AuditViolation
        self.flight_recorder = flight_recorder
        # Live-mode cap on the expensive per-entry checks (verdict cache,
        # futility memo); exhaustive mode ignores it.
        self.max_entries_per_check = max_entries_per_check
        self._plans_seen = 0
        self.violations_total = 0

    # -------------------------------------------------------- sampling

    def should_audit(self) -> bool:
        """Counter-stride sampling: audits plan k iff floor(k*rate)
        advances, giving exactly `rate` density with no RNG."""
        if self.sample_rate <= 0:
            return False
        self._plans_seen += 1
        k = self._plans_seen
        return math.floor(k * self.sample_rate) > math.floor(
            (k - 1) * self.sample_rate
        )

    # ----------------------------------------------------------- entry

    def audit_plan(
        self,
        planner,
        snapshot,
        exhaustive: bool = False,
        revision: int = 0,
        pending=None,
        desired=None,
        ledger=None,
    ) -> List[AuditViolation]:
        """Run every check against the given planner's just-completed
        plan() state. Publishes violations (metric, Event, flight record)
        and returns them. ``pending``/``desired`` are the plan's inputs
        and output — callers that have them (the partitioner controller)
        pass them so the incremental-plan shadow check can replan; callers
        auditing only structural invariants (chaos oracles, replay) omit
        them and that check idles."""
        violations: List[AuditViolation] = []
        violations += self.check_free_pool(snapshot)
        violations += self.check_mutation_clock(snapshot)
        violations += self.check_lacking_totals(planner.last_tracker)
        violations += self.check_verdict_cache(planner, snapshot, exhaustive)
        violations += self.check_carve_futility(planner, snapshot, exhaustive)
        violations += self.check_incremental_plan(
            planner, snapshot, pending, desired
        )
        violations += self.check_capacity_ledger(ledger)
        self.publish(violations, snapshot, revision)
        return violations

    def audit_sharded_plan(
        self,
        pool_runs,
        snapshot=None,
        exhaustive: bool = False,
        revision: int = 0,
        ledger=None,
    ) -> List[AuditViolation]:
        """Per-pool generalization of ``audit_plan``: under pool-sharded
        planning every pool's planner ran against its own snapshot shard,
        so every check — including the from-scratch ``incremental_plan``
        shadow replan — must hold pool by pool. ``pool_runs`` is an
        iterable of ``(pool, planner, pool_snapshot, pending, desired)``
        tuples; violation subjects are prefixed with the pool id so a
        drifting shard is named directly. The global ``snapshot`` (the
        unsharded base) is used only for Event targeting, and the ledger
        check stays cluster-scoped."""
        violations: List[AuditViolation] = []
        for pool, planner, pool_snapshot, pending, desired in pool_runs:
            pool_violations: List[AuditViolation] = []
            pool_violations += self.check_free_pool(pool_snapshot)
            pool_violations += self.check_mutation_clock(pool_snapshot)
            pool_violations += self.check_lacking_totals(planner.last_tracker)
            pool_violations += self.check_verdict_cache(
                planner, pool_snapshot, exhaustive
            )
            pool_violations += self.check_carve_futility(
                planner, pool_snapshot, exhaustive
            )
            pool_violations += self.check_incremental_plan(
                planner, pool_snapshot, pending, desired
            )
            for violation in pool_violations:
                violation.subject = f"pool={pool}/{violation.subject}"
            violations += pool_violations
        violations += self.check_capacity_ledger(ledger)
        self.publish(violations, snapshot, revision)
        return violations

    def publish(
        self, violations: List[AuditViolation], snapshot=None, revision: int = 0
    ) -> None:
        for violation in violations:
            metrics.AUDIT_VIOLATIONS.labels(check=violation.check).inc()
            self.violations_total += 1
            self._emit_event(violation, snapshot)
        if self.flight_recorder is not None and violations:
            self.flight_recorder.record_audit(
                revision=revision,
                violations=[v.to_dict() for v in violations],
            )

    def _emit_event(self, violation: AuditViolation, snapshot) -> None:
        if self.recorder is None or snapshot is None or not violation.node:
            return
        node = snapshot.get_nodes().get(violation.node)
        if node is None:
            return
        from nos_tpu.api.v1alpha1 import constants

        self.recorder.record(
            node.sim_node_info().node,
            constants.EVENT_REASON_AUDIT_VIOLATION,
            f"{violation.check}: {violation.detail}",
            type="Warning",
        )

    # ---------------------------------------------------------- checks

    def check_free_pool(self, snapshot) -> List[AuditViolation]:
        incremental = _nonzero(snapshot.free_slice_resources())
        truth = _nonzero(snapshot._compute_free_pool())
        if incremental == truth:
            return []
        return [
            AuditViolation(
                check="free_pool",
                subject="cluster",
                detail=f"incremental pool {incremental} != recomputed {truth}",
            )
        ]

    def check_mutation_clock(self, snapshot) -> List[AuditViolation]:
        out: List[AuditViolation] = []
        versions = {
            name: node.version for name, node in snapshot.get_nodes().items()
        }
        for name, version in versions.items():
            if version > snapshot.state_version:
                out.append(
                    AuditViolation(
                        check="mutation_clock",
                        subject=name,
                        detail=(
                            f"node version {version} ahead of "
                            f"state_version {snapshot.state_version}"
                        ),
                        node=name,
                    )
                )
        nonzero = [v for v in versions.values() if v]
        if len(nonzero) != len(set(nonzero)):
            dupes = sorted(v for v in set(nonzero) if nonzero.count(v) > 1)
            out.append(
                AuditViolation(
                    check="mutation_clock",
                    subject="cluster",
                    detail=f"duplicate mutation ticks across nodes: {dupes}",
                )
            )
        return out

    def check_lacking_totals(self, tracker) -> List[AuditViolation]:
        if tracker is None:
            return []
        out: List[AuditViolation] = []
        for accelerator, cached in tracker._totals_cache.items():
            truth: Dict[str, int] = {}
            for lacking in tracker._lacking.values():
                truth = res.sum_resources(
                    truth, tracker._convert_plain(lacking, accelerator)
                )
            if _nonzero(dict(cached)) != _nonzero(truth):
                out.append(
                    AuditViolation(
                        check="lacking_totals",
                        subject=accelerator or "(plain)",
                        detail=(
                            f"incremental totals {_nonzero(dict(cached))} "
                            f"!= recomputed {_nonzero(truth)}"
                        ),
                    )
                )
        return out

    def check_verdict_cache(
        self, planner, snapshot, exhaustive: bool = False
    ) -> List[AuditViolation]:
        entries = getattr(planner._verdict_cache, "entries", None)
        if not entries:
            return []
        # Recover each signature's normalized sim pod from the planner's
        # per-plan cache — the signature alone cannot be re-run.
        sim_by_signature = {
            cached[2]: cached[1] for cached in planner._sim_pod_cache.values()
        }
        nodes = snapshot.get_nodes()
        out: List[AuditViolation] = []
        checked = 0
        limit = None if exhaustive else self.max_entries_per_check
        for (signature, node_name, version), verdict in list(entries.items()):
            node = nodes.get(node_name)
            if node is None or node.version != version:
                # Stale key: the node moved on, the entry can never be
                # consulted for this state again — nothing to audit.
                continue
            sim_pod = sim_by_signature.get(signature)
            if sim_pod is None:
                continue
            fresh = planner._run_simulation(
                snapshot,
                node,
                sim_pod,
                publish=False,
                pre=planner._cacheable_pre,
                filters=planner._cacheable_filters,
            )
            if fresh != verdict:
                out.append(
                    AuditViolation(
                        check="verdict_cache",
                        subject=f"{node_name}@v{version}",
                        detail=(
                            f"cached verdict {verdict} != fresh plugin run "
                            f"{fresh} for signature on {node_name}"
                        ),
                        node=node_name,
                    )
                )
            checked += 1
            if limit is not None and checked >= limit:
                break
        return out

    def check_carve_futility(
        self, planner, snapshot, exhaustive: bool = False
    ) -> List[AuditViolation]:
        memo = getattr(planner, "_futility_cache", None)
        if not memo:
            return []
        nodes = snapshot.get_nodes()
        out: List[AuditViolation] = []
        checked = 0
        limit = None if exhaustive else self.max_entries_per_check
        for (node_name, version, lacking_items) in list(memo):
            node = nodes.get(node_name)
            if node is None or node.version != version:
                continue  # stale key, unreachable for this node state
            snapshot.fork()
            try:
                changed = snapshot.update_geometry_for(
                    node_name, dict(lacking_items)
                )
            finally:
                snapshot.revert()
            if changed:
                out.append(
                    AuditViolation(
                        check="carve_futility",
                        subject=f"{node_name}@v{version}",
                        detail=(
                            "futility memo claims carving toward "
                            f"{dict(lacking_items)} is a no-op, but a real "
                            "carve changed the geometry"
                        ),
                        node=node_name,
                    )
                )
            checked += 1
            if limit is not None and checked >= limit:
                break
        return out

    def check_incremental_plan(
        self, planner, snapshot, pending, desired
    ) -> List[AuditViolation]:
        """Warm-start correctness, checked end to end: when the audited
        plan() ran in incremental mode, replan the same pending set from
        scratch — fresh planner, fresh clone of the base snapshot, the
        recorded fairness ages — and require the identical desired
        PartitioningState and unserved reasons.

        A disagreement is arbitrated with a SECOND from-scratch run
        before it counts: the framework's uncacheable plugins read the
        live store, which other control loops may have advanced since the
        audited plan ran. Two shadows agreeing with each other but not
        with the incremental result is cache drift; shadows disagreeing
        between themselves means the inputs moved under us, which is a
        race, not a violation."""
        if desired is None or pending is None:
            return []
        if getattr(planner, "last_plan_mode", "full") != "incremental":
            return []
        from nos_tpu.partitioning.core.partition_state import (
            partitioning_state_equal,
            partitioning_state_to_dict,
        )

        first, first_unserved = self._shadow_plan(planner, snapshot, pending)
        desired_ok = partitioning_state_equal(desired, first)
        unserved_ok = dict(planner.last_unserved) == first_unserved
        if desired_ok and unserved_ok:
            return []
        second, second_unserved = self._shadow_plan(planner, snapshot, pending)
        if (
            not partitioning_state_equal(first, second)
            or first_unserved != second_unserved
        ):
            return []  # the shadow inputs themselves raced; inconclusive
        out: List[AuditViolation] = []
        if not desired_ok:
            out.append(
                AuditViolation(
                    check="incremental_plan",
                    subject="desired",
                    detail=(
                        "incremental desired state "
                        f"{partitioning_state_to_dict(desired)} != "
                        f"from-scratch {partitioning_state_to_dict(first)}"
                    ),
                )
            )
        if not unserved_ok:
            out.append(
                AuditViolation(
                    check="incremental_plan",
                    subject="unserved",
                    detail=(
                        f"incremental unserved {dict(planner.last_unserved)}"
                        f" != from-scratch {first_unserved}"
                    ),
                )
            )
        return out

    def check_capacity_ledger(self, ledger) -> List[AuditViolation]:
        """Shadow-recompute the capacity ledger's instantaneous state from
        scratch off its store and diff against the incremental view. The
        ledger itself declines the comparison (empty diff) when the store
        has advanced past its watermark — that window is a race between
        control loops, not drift."""
        if ledger is None:
            return []
        return [
            AuditViolation(
                check="capacity_ledger",
                subject="ledger",
                detail=diff,
            )
            for diff in ledger.self_check()
        ]

    @staticmethod
    def _shadow_plan(planner, snapshot, pending):
        """One from-scratch replan on a fresh clone of the base snapshot.
        Cloned nodes get version 0 (matching a fresh take_snapshot): the
        clone's mutation clock starts over, and preserving base versions
        would let a new tick collide with an inherited one."""
        from nos_tpu.partitioning.core.planner import Planner
        from nos_tpu.partitioning.core.snapshot import ClusterSnapshot

        nodes = {}
        for name, node in snapshot.get_nodes().items():
            clone = node.plan_clone()
            clone.version = 0
            nodes[name] = clone
        shadow_snapshot = ClusterSnapshot(nodes, codec=snapshot.codec)
        shadow = Planner(
            planner.framework,
            aging_chips_per_second=planner.aging_chips_per_second,
            verdict_cache_enabled=planner.verdict_cache_enabled,
            reuse_gang_trial=planner.reuse_gang_trial,
            futility_memo_enabled=planner.futility_memo_enabled,
        )
        desired = shadow.plan(
            shadow_snapshot,
            list(pending),
            pending_ages=dict(planner.last_pending_ages),
        )
        return desired, dict(shadow.last_unserved)


def build_auditor(
    sample_rate: float = 0.0, recorder=None, flight_recorder=None
) -> Optional[InvariantAuditor]:
    """Config seam: a zero rate means no auditor at all (no per-plan
    branch in the controller), not an auditor that never fires."""
    if sample_rate <= 0:
        return None
    return InvariantAuditor(
        sample_rate=sample_rate,
        recorder=recorder,
        flight_recorder=flight_recorder,
    )
