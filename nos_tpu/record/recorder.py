"""FlightRecorder: the control plane's decision log.

Two record streams share one bounded ring, ordered by a process-wide
sequence number:

- ``delta`` records — every store write (ADDED/MODIFIED/DELETED) for the
  kinds decisions read, serialized to the wire format (kube/serde.py) and
  keyed by the store revision the write was stamped with. Together they
  reconstruct the cluster state at any revision watermark.
- decision records — one per control cycle (``scheduler.cycle``,
  ``planner.plan``, ``quota.reconcile``, ``actuation``), carrying the
  revision watermark read at cycle entry (so replay knows exactly which
  deltas the decision observed), the decision outputs, monotonic/wall
  clock stamps, and links to the pod's journey trace id and Diagnosis.

Deltas arrive on a watch queue drained by a daemon thread, so they can
lag the decision records written synchronously by the deciding threads —
replay therefore orders deltas by revision (never by arrival) and
decisions by sequence. The ring is bounded (oldest records fall off) so a
long-lived process can always serve "the recent past" from
``/debug/record`` without growing memory.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

# Kinds replay needs to reconstruct decision inputs. Events are excluded
# on purpose: they are high-churn telemetry output, never decision input.
RECORDED_KINDS = (
    "Pod",
    "Node",
    "ConfigMap",
    "PodDisruptionBudget",
    "ElasticQuota",
    "CompositeElasticQuota",
)


def load_jsonl(path: str) -> List[dict]:
    """Parse an exported decision log back into record dicts."""
    records: List[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


class FlightRecorder:
    def __init__(self, capacity: int = 4096, seed: int = 0) -> None:
        from nos_tpu.timeline.sizes import SIZES

        self.capacity = capacity
        self._ring: "deque[dict]" = deque(maxlen=capacity)
        # Health-timeline leak watch: the ring is deque-bounded, so its
        # size.* series plateaus at capacity — growth past that means the
        # bound broke. Replace-by-name keeps the newest recorder current.
        SIZES.register("record.flight_ring", lambda: len(self._ring))
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._store = None
        self._queue = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Session header: wall/monotonic origin plus the (currently
        # unused, recorded for provenance) RNG seed — the clock/seed
        # stamps every later record's offsets are read against.
        self._append(
            "session.start",
            revision=0,
            seed=seed,
            wall_time=time.time(),
            monotonic=time.monotonic(),
        )

    # ------------------------------------------------------------ ring

    def _append(self, kind: str, **payload: Any) -> dict:
        record = {"seq": next(self._seq), "kind": kind, "ts": time.time()}
        record.update(payload)
        with self._lock:
            self._ring.append(record)
        return record

    def records(self) -> List[dict]:
        """Ring contents in sequence order (deep enough copies to be
        JSON-serialized by a concurrent reader)."""
        with self._lock:
            return list(self._ring)

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(r, sort_keys=True) for r in self.records())

    def export_jsonl(self, path: str) -> int:
        """Write the ring as JSONL; returns the record count."""
        records = self.records()
        with open(path, "w") as fh:
            for record in records:
                fh.write(json.dumps(record, sort_keys=True) + "\n")
        return len(records)

    # ----------------------------------------------------- delta stream

    def attach(self, store, kinds: Iterable[str] = RECORDED_KINDS) -> None:
        """Subscribe to the store's watch stream and record every write to
        the given kinds as a ``delta``. Existing objects replay as ADDED
        (informer list+watch), so a recorder attached before traffic
        starts captures the full initial state."""
        if self._store is not None:
            raise RuntimeError("recorder already attached")
        self._store = store
        self._queue = store.watch(kinds, name="flight-recorder")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._drain_loop, name="flight-recorder", daemon=True
        )
        self._thread.start()

    def detach(self) -> None:
        """Stop the delta stream and drain whatever is still queued, so an
        export right after detach() holds every write made before it."""
        if self._store is None:
            return
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self._drain_pending()
        self._store.stop_watch(self._queue)
        self._store = None
        self._queue = None

    def _drain_loop(self) -> None:
        import queue as queue_mod

        while not self._stop.is_set():
            try:
                event = self._queue.get(timeout=0.1)
            except queue_mod.Empty:
                continue
            self._record_delta(event)

    def _drain_pending(self) -> None:
        import queue as queue_mod

        while True:
            try:
                event = self._queue.get_nowait()
            except queue_mod.Empty:
                return
            self._record_delta(event)

    def _record_delta(self, event) -> None:
        from nos_tpu.kube import serde

        try:
            wire = serde.to_wire(event.object)
        except (KeyError, AttributeError):
            return  # kind without a wire codec; decisions never read it
        self._append(
            "delta",
            type=event.type,
            # The event's apply-sequence stamp when the store provides one
            # (KubeApiStore: apiserver rvs can reach the cache out of
            # order, so only the apply order keys replay correctly); the
            # in-memory store's rv is already its apply order.
            revision=event.revision or event.object.metadata.resource_version,
            object=wire,
        )

    # -------------------------------------------------- decision stream

    def record_session_meta(self, **meta: Any) -> None:
        """Extra session-level facts replay needs (scheduler name, gang
        timeout, ...), folded into the session.start header."""
        with self._lock:
            for record in self._ring:
                if record["kind"] == "session.start":
                    record.update(meta)
                    return

    def record_scheduler_cycle(
        self,
        *,
        pod: str,
        revision: int,
        decision: str,
        node: str = "",
        bound: Optional[List[List[str]]] = None,
        victims: Optional[List[str]] = None,
        message: str = "",
        trace_id: str = "",
        diagnosis: Optional[dict] = None,
        settled: bool = True,
    ) -> None:
        self._append(
            "scheduler.cycle",
            pod=pod,
            revision=revision,
            decision=decision,
            node=node,
            bound=bound or [],
            victims=victims or [],
            message=message,
            trace_id=trace_id,
            diagnosis=diagnosis,
            settled=settled,
            monotonic=time.monotonic(),
        )

    def record_plan(
        self,
        *,
        kind: str,
        revision: int,
        pending: List[str],
        pending_ages: Dict[str, float],
        plan_id: str,
        desired: dict,
        unserved: Dict[str, str],
        applied: int,
        trace_id: str = "",
    ) -> None:
        self._append(
            "planner.plan",
            partitioner_kind=kind,
            revision=revision,
            pending=pending,
            pending_ages=pending_ages,
            plan_id=plan_id,
            desired=desired,
            unserved=unserved,
            applied=applied,
            trace_id=trace_id,
            monotonic=time.monotonic(),
        )

    def record_quota_reconcile(
        self,
        *,
        quota: str,
        revision: int,
        used: Dict[str, float],
        flips: List[List[str]],
    ) -> None:
        """One quota reconcile pass: published usage plus the capacity
        label flips ([pod key, new label] pairs) it produced."""
        self._append(
            "quota.reconcile",
            quota=quota,
            revision=revision,
            used=used,
            flips=flips,
        )

    def record_actuation(
        self, *, kind: str, plan_id: str, revision: int, applied: int
    ) -> None:
        self._append(
            "actuation",
            partitioner_kind=kind,
            plan_id=plan_id,
            revision=revision,
            applied=applied,
        )

    def record_pool_escalation(
        self, *, kind: str, pool: str, revision: int, reason: str
    ) -> None:
        """A process-backend pool whose worker could not serve the cycle
        (crash, wedge, untrusted frame): the pool was planned in-parent
        and its worker respawns from a fresh wire image. Replay ignores
        the record (the escalated plan itself is in the ordinary plan
        record); it exists so a postmortem can line worker deaths up
        against the cycles they degraded."""
        self._append(
            "pool.escalation",
            partitioner_kind=kind,
            pool=pool,
            revision=revision,
            reason=reason,
        )

    def record_audit(self, *, revision: int, violations: List[dict]) -> None:
        self._append("audit", revision=revision, violations=violations)

    def record_capacity(
        self,
        *,
        revision: int,
        now: float,
        reason: Optional[str],
        totals: dict,
        trace_id: str = "",
    ) -> None:
        """One integrating CapacityLedger.observe(): the watermark it
        drained to, the wall timestamp it integrated to (``now`` — replay
        re-integrates from these, never from its own clock), the pending
        reason chosen for the next interval, and the cumulative
        chip-second totals for zero-drift comparison."""
        self._append(
            "capacity.observe",
            revision=revision,
            now=now,
            reason=reason,
            totals=totals,
            trace_id=trace_id,
        )

    def record_forecast(
        self,
        *,
        revision: int,
        now: float,
        gangs: List[dict],
        backfill_unsafe: int,
        advisor_validated: bool,
        trace_id: str = "",
    ) -> None:
        """One forecast cycle: every published gang ETA (the stamps the
        accuracy auditor later joins against observed binds), the
        backfill-unsafe pair count, and whether the advisor's proposal
        validated in its shadow sim."""
        self._append(
            "forecast.cycle",
            revision=revision,
            now=now,
            gangs=gangs,
            backfill_unsafe=backfill_unsafe,
            advisor_validated=advisor_validated,
            trace_id=trace_id,
        )

    def record_forecast_outcome(
        self,
        *,
        gang: str,
        now: float,
        stage: str,
        eta_seconds: Optional[float],
        actual_seconds: float,
        wait_seconds: float,
        calibration: dict,
    ) -> None:
        """One forecast-vs-observed join at gang-bound, carrying the
        running calibration payload so replay can re-feed the outcomes
        through a shadow CalibrationTracker and compare bit-exactly."""
        self._append(
            "forecast.outcome",
            gang=gang,
            now=now,
            stage=stage,
            eta_seconds=eta_seconds,
            actual_seconds=actual_seconds,
            wait_seconds=wait_seconds,
            calibration=calibration,
        )

    def record_timeline_finding(
        self,
        *,
        t: float,
        detector: str,
        series: str,
        window: List[List[float]],
        params: dict,
        verdict: dict,
        stacks: Optional[List[str]] = None,
    ) -> None:
        """One new health-timeline detector finding, carrying the exact
        detector inputs (the sample window and parameters) next to the
        verdict so replay can re-run the pure detector over them and
        compare the recomputed verdict bit-exactly. ``stacks`` are the
        wedged thread's profiler stacks — operator context, excluded
        from the bit-exact comparison."""
        self._append(
            "timeline.finding",
            t=t,
            detector=detector,
            series=series,
            window=window,
            params=params,
            verdict=verdict,
            stacks=stacks or [],
        )
