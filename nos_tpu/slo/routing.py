"""Per-replica routing shim: the diurnal workload meets the SimCluster.

The open-loop driver (slo/driver.py) drives ONE engine per model. The
autoscaler changes replica counts mid-run, so this module adds the
missing layer: a router that keeps one cost-model replica
(``SimReplicaEngine``) per live replica Pod, spreads each model's
arrivals round-robin across them, and holds a backlog while a
scaled-to-zero model has no replicas — the backlog is what turns a cold
start into an honest TTFT penalty, because requests keep their original
arrival stamps and wait out the wake-up in virtual time.

``SimReplicaEngine`` is deliberately NOT serve/engine.py: that engine
runs a real JAX model. A replica here is the cost model alone — the
same ``ServeTelemetry`` hooks, the same ``VirtualServeClock`` arithmetic
(prefill cost per token, one batched tick per decode round), no device —
so a bench can run dozens of replica-epochs in milliseconds while
producing the same latency bookkeeping the real engine would.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from nos_tpu.controllers.autoscaler.signals import SignalRegistry
from nos_tpu.serve.telemetry import ServeTelemetry, VirtualServeClock
from nos_tpu.slo.driver import Arrival


@dataclass
class _SimRequest:
    """The duck-typed surface ServeTelemetry reads off a request."""

    id: int
    prompt: List[int]
    max_new_tokens: int
    adapter: int = 0


class SimReplicaEngine:
    """One replica's continuous-batching cost model.

    Engine-shaped for the driver loop (``submit`` / ``busy`` / ``step`` /
    ``telemetry``): admission fills ``max_slots`` in submit order, each
    ``step`` runs one batched decode tick (every active slot emits one
    token — batching makes the tick cost independent of slot count, like
    the real engine's fused decode), and a request retires at its token
    budget.
    """

    def __init__(
        self,
        model: str,
        max_slots: int = 8,
        ready_t: float = 0.0,
        tick_cost_s: float = 0.008,
        prefill_token_cost_s: float = 0.0002,
        ttft_target_s: Optional[float] = None,
        e2e_target_s: Optional[float] = None,
        on_complete=None,
    ) -> None:
        self.model = model
        self.max_slots = max_slots
        self.telemetry = ServeTelemetry(
            model=model,
            clock=VirtualServeClock(
                tick_cost_s=tick_cost_s,
                prefill_token_cost_s=prefill_token_cost_s,
                start=ready_t,
            ),
            ttft_target_s=ttft_target_s,
            e2e_target_s=e2e_target_s,
            on_complete=on_complete,
        )
        self._next_id = 0
        self._queue: List[_SimRequest] = []
        # Active slots in admission order: request -> tokens emitted.
        self._active: List[List] = []

    @property
    def busy(self) -> bool:
        return bool(self._queue or self._active)

    def submit(self, arrival: Arrival, submit_at: float) -> None:
        req = _SimRequest(
            id=self._next_id,
            prompt=list(arrival.prompt),
            max_new_tokens=max(1, arrival.max_new_tokens),
            adapter=arrival.adapter,
        )
        self._next_id += 1
        self.telemetry.on_submit(req, bucket=0, submit_at=submit_at)
        self._queue.append(req)

    def step(self, chunks: int = 1) -> None:
        while self._queue and len(self._active) < self.max_slots:
            req = self._queue.pop(0)
            with self.telemetry.admit_span(req):
                with self.telemetry.prefill_span(
                    req, len(req.prompt), path="sim"
                ):
                    pass
            self._active.append([req, 0])
        if not self._active:
            return
        with self.telemetry.decode_span(
            chunks=chunks, active_slots=len(self._active)
        ):
            self.telemetry.on_decode_ticks(1)
        retired = []
        for slot in self._active:
            req, emitted = slot
            if emitted == 0:
                self.telemetry.on_first_token(req)
            slot[1] = emitted + 1
            if slot[1] >= req.max_new_tokens:
                retired.append(slot)
        for slot in retired:
            self._active.remove(slot)
            self.telemetry.on_retire(slot[0], slot[1])


class ReplicaRouter:
    """Spreads each model's arrivals over its live replica engines.

    The bench calls ``sync_replicas`` after every control epoch (with
    the replica pod names the autoscaler + scheduler actually produced)
    and ``drive`` with the epoch's arrivals. Zero replicas = arrivals
    accumulate in the model's backlog and surface as queue-depth demand
    in the signal registry; the next sync's fresh replicas inherit the
    backlog with the original arrival stamps.
    """

    def __init__(
        self,
        signals: Optional[SignalRegistry] = None,
        max_slots: int = 8,
        ttft_targets: Optional[Dict[str, float]] = None,
        e2e_targets: Optional[Dict[str, float]] = None,
        on_complete: Optional[Dict[str, object]] = None,
    ) -> None:
        self.signals = signals
        self.max_slots = max_slots
        self.ttft_targets = ttft_targets or {}
        self.e2e_targets = e2e_targets or {}
        self.on_complete = on_complete or {}
        # model -> replica pod name -> engine (insertion irrelevant:
        # routing always walks sorted names).
        self.replicas: Dict[str, Dict[str, SimReplicaEngine]] = {}
        self.backlog: Dict[str, List[Arrival]] = {}
        self._rr: Dict[str, int] = {}

    # ----------------------------------------------------------- fleet

    def sync_replicas(
        self, model: str, replica_names: List[str], ready_t: float
    ) -> List[str]:
        """Reconcile the engine set to the given pod names; new replicas
        come up at ``ready_t`` (epoch end + cold-start model cost).
        Returns the names created."""
        engines = self.replicas.setdefault(model, {})
        wanted = set(replica_names)
        for name in [n for n in engines if n not in wanted]:
            del engines[name]
        created = []
        for name in sorted(wanted - set(engines)):
            engines[name] = SimReplicaEngine(
                model,
                max_slots=self.max_slots,
                ready_t=ready_t,
                ttft_target_s=self.ttft_targets.get(model),
                e2e_target_s=self.e2e_targets.get(model),
                on_complete=self.on_complete.get(model),
            )
            created.append(name)
        return created

    def engines(self, model: str) -> List[SimReplicaEngine]:
        return [e for _, e in sorted(self.replicas.get(model, {}).items())]

    def clock_now(self, model: str) -> float:
        return max(
            (e.telemetry.clock.now() for e in self.engines(model)),
            default=0.0,
        )

    # ----------------------------------------------------------- driving

    def drive(
        self, model: str, arrivals: List[Arrival], epoch_end: float
    ) -> int:
        """Queue the epoch's arrivals behind any backlog, drive the
        model's replicas to completion in virtual time, then align every
        replica clock to ``epoch_end``. Returns requests completed."""
        backlog = self.backlog.setdefault(model, [])
        backlog.extend(arrivals)
        last_t = max((a.t for a in backlog), default=None)
        engines = self.engines(model)
        completed = 0
        if engines:
            names = sorted(self.replicas[model])
            rr = self._rr.get(model, 0)
            per_engine: Dict[str, List[Arrival]] = {n: [] for n in names}
            for a in backlog:
                per_engine[names[rr % len(names)]].append(a)
                rr += 1
            self._rr[model] = rr
            backlog.clear()
            for name in names:
                completed += self._drive_engine(
                    self.replicas[model][name], per_engine[name], epoch_end
                )
        if self.signals is not None:
            if last_t is not None:
                self.signals.note_arrival(model, last_t, len(backlog))
            else:
                self.signals.update(model, queue_depth=len(backlog))
        return completed

    @staticmethod
    def _drive_engine(
        engine: SimReplicaEngine, arrivals: List[Arrival], epoch_end: float
    ) -> int:
        clock = engine.telemetry.clock
        before = len(engine.telemetry.completed)
        i = 0
        while i < len(arrivals) or engine.busy:
            while i < len(arrivals) and arrivals[i].t <= clock.now():
                engine.submit(arrivals[i], submit_at=arrivals[i].t)
                i += 1
            if engine.busy:
                engine.step()
            elif i < len(arrivals):
                clock.advance_to(arrivals[i].t)
        clock.advance_to(epoch_end)
        return len(engine.telemetry.completed) - before
