"""Open-loop workload driver for the serving engines.

Closed-loop drivers (submit, wait, submit) measure the server at the
client's pace and hide queueing collapse — the coordinated-omission
trap. This driver is open-loop: arrivals are a seeded Poisson process
shaped by a diurnal rate curve, generated up front as a pure function of
the config (``build_arrivals``), and each request is stamped with its
*arrival* time no matter when the engine gets around to admitting it —
queue wait and TTFT honestly include scheduling delay under overload.

Determinism: the driver runs each engine on its own
``VirtualServeClock`` — time advances from the engine's cost model
(seconds per decode tick / prefill token), not the host's wall clock, so
every latency in the report is a pure function of (seed, config, engine
scheduling). ``bench_serve.py`` commits the resulting
``BENCH_serve.json``; two runs at the same seed are bit-identical.

Model skew: each ``ModelProfile`` owns a share of the arrival stream
(hot/cold replicas), and each model name maps to its own engine — the
replica-per-model serving shape, so a hot model's queue cannot starve a
cold one and the per-model SLO verdicts are independent.
"""
from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from nos_tpu.serve.engine import Engine, GenRequest
from nos_tpu.serve.telemetry import RequestRecord, VirtualServeClock
from nos_tpu.slo.engine import SLOEngine
from nos_tpu.util.profiling import PROFILER
from nos_tpu.util.tracing import TRACER


@dataclass(frozen=True)
class ModelProfile:
    """One model's share of the workload."""

    name: str
    weight: float = 1.0  # relative share of arrivals
    prompt_tokens: tuple = (8, 32)  # inclusive range
    max_new_tokens: tuple = (8, 48)  # inclusive range
    adapter: int = 0


@dataclass(frozen=True)
class WorkloadConfig:
    seed: int = 0
    duration_s: float = 60.0
    rate_rps: float = 2.0  # mean arrival rate across all models
    # rate(t) = rate_rps * (1 + amplitude * sin(2*pi*t/period)): 0 = flat,
    # 0.5 = peaks at 1.5x and troughs at 0.5x the mean.
    diurnal_amplitude: float = 0.0
    diurnal_period_s: float = 60.0
    vocab: int = 256
    models: Sequence[ModelProfile] = field(
        default_factory=lambda: (ModelProfile(name="default"),)
    )


@dataclass(frozen=True)
class Arrival:
    t: float
    model: str
    prompt: List[int]
    max_new_tokens: int
    adapter: int = 0


def build_arrivals(config: WorkloadConfig) -> List[Arrival]:
    """The whole arrival schedule as a pure function of the config.

    Poisson process via thinning: draw candidates at the PEAK rate, keep
    each with probability rate(t)/peak — an exact non-homogeneous
    Poisson sampler, and the accept/reject draws stay aligned with the
    seed no matter how the rate curve moves.
    """
    if not config.models:
        raise ValueError("workload needs at least one ModelProfile")
    if not 0.0 <= config.diurnal_amplitude <= 1.0:
        raise ValueError("diurnal_amplitude must be in [0, 1]")
    rng = random.Random(config.seed)
    peak = config.rate_rps * (1.0 + config.diurnal_amplitude)
    if peak <= 0:
        return []
    weights = [max(0.0, m.weight) for m in config.models]
    total_w = sum(weights)
    if total_w <= 0:
        raise ValueError("model weights must sum to > 0")
    arrivals: List[Arrival] = []
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= config.duration_s:
            break
        rate = config.rate_rps * (
            1.0
            + config.diurnal_amplitude
            * math.sin(2.0 * math.pi * t / config.diurnal_period_s)
        )
        if rng.random() * peak > rate:
            continue  # thinned candidate; draws consumed, alignment kept
        pick = rng.random() * total_w
        model = config.models[-1]
        for m, w in zip(config.models, weights):
            pick -= w
            if pick < 0:
                model = m
                break
        n_prompt = rng.randint(*model.prompt_tokens)
        arrivals.append(
            Arrival(
                t=t,
                model=model.name,
                prompt=[rng.randrange(config.vocab) for _ in range(n_prompt)],
                max_new_tokens=rng.randint(*model.max_new_tokens),
                adapter=model.adapter,
            )
        )
    return arrivals


def percentiles(values: Sequence[float]) -> Dict[str, float]:
    """p50/p95/p99 by the nearest-rank method (deterministic, no
    interpolation ambiguity across platforms)."""
    if not values:
        return {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    ordered = sorted(values)
    out = {}
    for p, key in ((50, "p50"), (95, "p95"), (99, "p99")):
        rank = max(1, math.ceil(p / 100.0 * len(ordered)))
        out[key] = round(ordered[rank - 1], 6)
    return out


class OpenLoopDriver:
    """Drives one engine per model through a shared arrival schedule.

    Each engine's telemetry must carry a ``VirtualServeClock`` (the
    constructor checks): the driver submits every arrival with
    ``submit_at`` = its generated arrival time, steps the engine while
    it is busy (the engine's cost model advances the clock), and jumps
    the clock forward over idle gaps. Replicas are independent, so
    models are driven to completion one at a time — the interleaving a
    shared wall clock would force does not exist in virtual time.
    """

    def __init__(
        self,
        engines: Dict[str, Engine],
        config: WorkloadConfig,
        slo: Optional[SLOEngine] = None,
    ) -> None:
        for profile in config.models:
            if profile.name not in engines:
                raise ValueError(f"no engine for model {profile.name!r}")
            clock = engines[profile.name].telemetry.clock
            if not isinstance(clock, VirtualServeClock):
                raise ValueError(
                    f"engine {profile.name!r} needs a VirtualServeClock "
                    "(wall-clock engines cannot produce a deterministic "
                    "report)"
                )
        self.engines = engines
        self.config = config
        self.slo = slo
        self.records: Dict[str, List[RequestRecord]] = {}

    # ------------------------------------------------------------ driving

    def _drive_one(self, model: str, arrivals: List[Arrival]) -> None:
        engine = self.engines[model]
        telemetry = engine.telemetry
        clock = telemetry.clock
        done_before = set(telemetry.completed)
        i = 0
        # The serve loop is a registered profiler target: /debug/profile
        # decomposes its samples into the serve.admit / serve.prefill /
        # serve.batch_decode phases the engine spans publish.
        with PROFILER.registered(f"serve-{model}"):
            with TRACER.span("serve.drive", model=model, arrivals=len(arrivals)):
                while i < len(arrivals) or engine.busy:
                    while i < len(arrivals) and arrivals[i].t <= clock.now():
                        a = arrivals[i]
                        engine.submit(
                            GenRequest(
                                prompt=list(a.prompt),
                                max_new_tokens=a.max_new_tokens,
                                adapter=a.adapter,
                            ),
                            submit_at=a.t,
                        )
                        i += 1
                    if engine.busy:
                        engine.step(chunks=1)
                    elif i < len(arrivals):
                        clock.advance_to(arrivals[i].t)
        self.records[model] = [
            rec
            for rid, rec in telemetry.completed.items()
            if rid not in done_before
        ]

    def run(self) -> Dict[str, Any]:
        arrivals = build_arrivals(self.config)
        by_model: Dict[str, List[Arrival]] = {
            m.name: [] for m in self.config.models
        }
        for a in arrivals:
            by_model[a.model].append(a)
        for model in sorted(by_model):
            self._drive_one(model, by_model[model])
        return self.report()

    # ---------------------------------------------------------- reporting

    @staticmethod
    def _stats(records: List[RequestRecord]) -> Dict[str, Any]:
        tokens = sum(r.tokens for r in records)
        good = [r for r in records if r.good]
        last_retire = max((r.retire_t or 0.0 for r in records), default=0.0)
        return {
            "requests": len(records),
            "tokens": tokens,
            "ttft_s": percentiles([r.ttft_s for r in records if r.ttft_s is not None]),
            "tpot_s": percentiles(
                [r.tpot_s for r in records if r.tpot_s is not None and r.tokens > 1]
            ),
            "e2e_s": percentiles([r.e2e_s for r in records if r.e2e_s is not None]),
            "queue_wait_s": percentiles(
                [r.queue_wait_s for r in records if r.queue_wait_s is not None]
            ),
            "goodput": {
                "good_requests": len(good),
                "request_fraction": round(len(good) / len(records), 6)
                if records
                else 0.0,
                "good_tokens": sum(r.tokens for r in good),
                "good_tokens_per_s": round(
                    sum(r.tokens for r in good) / last_retire, 6
                )
                if last_retire > 0
                else 0.0,
            },
        }

    def report(self) -> Dict[str, Any]:
        models = {
            model: self._stats(records)
            for model, records in sorted(self.records.items())
        }
        everything = [r for records in self.records.values() for r in records]
        out: Dict[str, Any] = {
            "workload": {
                "seed": self.config.seed,
                "duration_s": self.config.duration_s,
                "rate_rps": self.config.rate_rps,
                "diurnal_amplitude": self.config.diurnal_amplitude,
                "diurnal_period_s": self.config.diurnal_period_s,
                "models": [
                    {
                        "name": m.name,
                        "weight": m.weight,
                        "prompt_tokens": list(m.prompt_tokens),
                        "max_new_tokens": list(m.max_new_tokens),
                    }
                    for m in self.config.models
                ],
            },
            "models": models,
            "aggregate": self._stats(everything),
        }
        if self.slo is not None:
            # Evaluate at the latest per-replica virtual instant: every
            # replica's whole run lands inside the slow window.
            now = max(
                (e.telemetry.clock.now() for e in self.engines.values()),
                default=0.0,
            )
            evaluation = self.slo.evaluate(now=now)
            out["slo"] = {
                "specs": [s["spec"] for s in evaluation["slos"]],
                "verdicts": {
                    s["slo"]: {
                        "compliant": s["compliant"],
                        "burn_rate_fast": s["fast"]["burn_rate"],
                        "burn_rate_slow": s["slow"]["burn_rate"],
                        "error_budget_remaining": s["error_budget_remaining"],
                    }
                    for s in evaluation["slos"]
                },
            }
        return out
