"""Serving SLOs: declarative specs, burn-rate evaluation, workload driver."""
from nos_tpu.slo.driver import (
    Arrival,
    ModelProfile,
    OpenLoopDriver,
    WorkloadConfig,
    build_arrivals,
)
from nos_tpu.slo.engine import SLOEngine, SLOSpec

__all__ = [
    "Arrival",
    "ModelProfile",
    "OpenLoopDriver",
    "SLOEngine",
    "SLOSpec",
    "WorkloadConfig",
    "build_arrivals",
]
