"""Burn-rate SLO engine over the serving telemetry.

ROADMAP item 3's autoscaler scales "on p95 latency and queue depth" —
which presupposes someone has *defined* the latency objective. This
module is that definition plus its evaluator, following the
multi-window multi-burn-rate methodology (Google SRE workbook): an SLO
is a target fraction of good events, the error budget is the allowed
bad fraction, and the burn rate over a window is

    burn = bad_fraction(window) / (1 - objective)

so burn 1.0 exactly exhausts the budget at the window's scale, and the
same threshold works for a fast window (paging on sudden regressions)
and a slow window (the compliance verdict).

Specs are declarative one-liners:

- ``"p95 ttft < 300ms"`` — 95% of requests must see TTFT under 300 ms.
  Metric is one of ``ttft``/``tpot``/``e2e``/``queue_wait``; the
  percentile IS the objective (a request over the threshold is a bad
  event, and at most 5% may be bad).
- ``"availability 99.9%"`` — 99.9% of requests must be *good* in the
  goodput sense (met the engine's per-request latency targets; see
  serve/telemetry.py). A request the engine never completed would also
  be bad, but the evaluator only sees retired requests — wire timeouts
  upstream if you need them.

Every completed request is one event per spec. ``evaluate()`` walks the
bounded record window once and publishes ``nos_tpu_slo_burn_rate
{slo,window}``, ``nos_tpu_slo_compliant{slo}`` and
``nos_tpu_slo_error_budget_remaining{slo}``; ``debug_payload()`` is the
``/debug/slo`` rollup, with recent violations linking into
``/debug/traces`` by the request's journey trace id.
"""
from __future__ import annotations

import re
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from nos_tpu.serve.telemetry import RequestRecord, ServeClock
from nos_tpu.util.metrics import REGISTRY

SLO_BURN_RATE = REGISTRY.gauge(
    "nos_tpu_slo_burn_rate",
    "Error-budget burn rate per SLO and window (by slo, window=fast|slow): "
    "bad fraction / allowed bad fraction — 1.0 burns exactly the budget, "
    "sustained >1.0 on the slow window means non-compliance",
)
SLO_COMPLIANT = REGISTRY.gauge(
    "nos_tpu_slo_compliant",
    "1 when the SLO's slow-window good fraction meets its objective "
    "(vacuously compliant with no traffic in the window) (by slo)",
)
SLO_BUDGET_REMAINING = REGISTRY.gauge(
    "nos_tpu_slo_error_budget_remaining",
    "Fraction of the slow-window error budget not yet consumed "
    "(1 - burn rate, clamped to [0, 1]) (by slo)",
)

# Latency metrics a spec may target — properties of RequestRecord.
_METRICS = ("ttft", "tpot", "e2e", "queue_wait")

_LATENCY_RE = re.compile(
    r"^p(?P<pct>\d{1,2}(?:\.\d+)?)\s+(?P<metric>[a-z][a-z0-9_]*)\s*<\s*"
    r"(?P<value>\d+(?:\.\d+)?)\s*(?P<unit>ms|s)$"
)
_AVAIL_RE = re.compile(r"^availability\s+(?P<pct>\d{1,2}(?:\.\d+)?)%$")


@dataclass(frozen=True)
class SLOSpec:
    """One parsed objective. ``metric`` is a latency name or
    ``"availability"``; latency specs carry the threshold whose
    violation makes a request a bad event."""

    raw: str
    name: str
    metric: str
    objective: float  # required good fraction, e.g. 0.95
    threshold_s: Optional[float] = None  # latency specs only

    @staticmethod
    def parse(text: str) -> "SLOSpec":
        spec = text.strip().lower()
        m = _LATENCY_RE.match(spec)
        if m:
            metric = m.group("metric")
            if metric not in _METRICS:
                raise ValueError(
                    f"unknown SLO metric {metric!r}: pick one of "
                    f"{', '.join(_METRICS)}"
                )
            pct = float(m.group("pct"))
            if not 0 < pct < 100:
                raise ValueError(f"percentile must be in (0, 100): {text!r}")
            value = float(m.group("value"))
            threshold = value / 1000.0 if m.group("unit") == "ms" else value
            unit = m.group("unit")
            shown = f"{value:g}{unit}"
            return SLOSpec(
                raw=text.strip(),
                name=f"{metric}_p{m.group('pct')}_lt_{shown}",
                metric=metric,
                objective=pct / 100.0,
                threshold_s=threshold,
            )
        m = _AVAIL_RE.match(spec)
        if m:
            pct = float(m.group("pct"))
            if not 0 < pct < 100:
                raise ValueError(f"availability must be in (0, 100): {text!r}")
            return SLOSpec(
                raw=text.strip(),
                name=f"availability_{m.group('pct')}",
                metric="availability",
                objective=pct / 100.0,
            )
        raise ValueError(
            f"unparseable SLO {text!r}: expected 'p<pct> "
            f"<ttft|tpot|e2e|queue_wait> < <n><ms|s>' or "
            f"'availability <pct>%'"
        )

    def is_bad(self, event: "_Event") -> bool:
        if self.metric == "availability":
            return not event.ok
        value = event.metrics.get(self.metric)
        # A stage that never happened (no first token, etc.) is bad: the
        # user saw the miss either way.
        return value is None or value > self.threshold_s


@dataclass(frozen=True)
class _Event:
    t: float
    metrics: Dict[str, Optional[float]]
    ok: bool
    trace_id: str


class SLOEngine:
    """Windowed burn-rate evaluator over completed-request events.

    Feed it retired requests (``record``; the engine telemetry's
    ``on_complete`` callback is the natural wire) and call ``evaluate``
    periodically — every call re-publishes the SLO gauges and returns
    the rollup dict that ``/debug/slo`` serves.
    """

    MAX_VIOLATIONS = 32

    def __init__(
        self,
        specs: Sequence["SLOSpec | str"],
        clock: Optional[ServeClock] = None,
        fast_window_s: float = 60.0,
        slow_window_s: float = 600.0,
        max_records: int = 65536,
    ) -> None:
        self.specs: List[SLOSpec] = [
            s if isinstance(s, SLOSpec) else SLOSpec.parse(s) for s in specs
        ]
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {sorted(names)}")
        self.clock = clock or ServeClock()
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self._events: "deque[_Event]" = deque(maxlen=max_records)
        self._violations: "deque[dict]" = deque(maxlen=self.MAX_VIOLATIONS)
        self._seen = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ intake

    def latency_targets(self) -> Dict[str, float]:
        """Tightest latency threshold per metric — what the engine's
        goodput targets (ServeTelemetry ttft_target_s / e2e_target_s)
        should be set to so 'good' and 'available' agree."""
        targets: Dict[str, float] = {}
        for spec in self.specs:
            if spec.threshold_s is None:
                continue
            prev = targets.get(spec.metric)
            if prev is None or spec.threshold_s < prev:
                targets[spec.metric] = spec.threshold_s
        return targets

    def record(self, rec: RequestRecord) -> None:
        """One retired request becomes one event per SLO."""
        event = _Event(
            t=rec.retire_t if rec.retire_t is not None else self.clock.now(),
            metrics={
                "ttft": rec.ttft_s,
                "tpot": rec.tpot_s,
                "e2e": rec.e2e_s,
                "queue_wait": rec.queue_wait_s,
            },
            ok=bool(rec.good),
            trace_id=rec.trace_id,
        )
        violated = [s.name for s in self.specs if s.is_bad(event)]
        with self._lock:
            self._seen += 1
            self._events.append(event)
            if violated:
                entry = {
                    "t": round(event.t, 6),
                    "request": rec.id,
                    "model": rec.model,
                    "slos": violated,
                    "ttft_s": round(event.metrics["ttft"] or 0.0, 6),
                    "e2e_s": round(event.metrics["e2e"] or 0.0, 6),
                }
                if event.trace_id:
                    entry["trace"] = f"/debug/traces?id={event.trace_id}"
                self._violations.append(entry)

    # -------------------------------------------------------- evaluation

    def _window_stats(
        self, spec: SLOSpec, events: List[_Event], now: float, window_s: float
    ) -> Dict[str, Any]:
        total = bad = 0
        for event in events:
            if event.t > now - window_s:
                total += 1
                if spec.is_bad(event):
                    bad += 1
        allowed = 1.0 - spec.objective
        bad_fraction = bad / total if total else 0.0
        burn = bad_fraction / allowed if allowed > 0 else 0.0
        return {
            "requests": total,
            "bad": bad,
            "bad_fraction": round(bad_fraction, 6),
            "burn_rate": round(burn, 6),
        }

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Re-evaluate every spec over both windows, publish the gauges,
        and return the per-SLO rollup (the ``/debug/slo`` document sans
        violation feed)."""
        if now is None:
            now = self.clock.now()
        with self._lock:
            events = list(self._events)
            seen = self._seen
        out: Dict[str, Any] = {
            "now": round(now, 6),
            "windows": {"fast_s": self.fast_window_s, "slow_s": self.slow_window_s},
            "requests_seen": seen,
            "slos": [],
        }
        for spec in self.specs:
            fast = self._window_stats(spec, events, now, self.fast_window_s)
            slow = self._window_stats(spec, events, now, self.slow_window_s)
            compliant = slow["burn_rate"] <= 1.0
            budget_remaining = round(
                min(1.0, max(0.0, 1.0 - slow["burn_rate"])), 6
            )
            SLO_BURN_RATE.labels(slo=spec.name, window="fast").set(
                fast["burn_rate"]
            )
            SLO_BURN_RATE.labels(slo=spec.name, window="slow").set(
                slow["burn_rate"]
            )
            SLO_COMPLIANT.labels(slo=spec.name).set(1.0 if compliant else 0.0)
            SLO_BUDGET_REMAINING.labels(slo=spec.name).set(budget_remaining)
            out["slos"].append(
                {
                    "slo": spec.name,
                    "spec": spec.raw,
                    "metric": spec.metric,
                    "objective": spec.objective,
                    "threshold_s": spec.threshold_s,
                    "fast": fast,
                    "slow": slow,
                    "compliant": compliant,
                    "error_budget_remaining": budget_remaining,
                }
            )
        return out

    def debug_payload(self) -> Dict[str, Any]:
        """The ``/debug/slo`` document: live rollup + recent violations
        with ``/debug/traces`` links."""
        payload = self.evaluate()
        with self._lock:
            payload["recent_violations"] = list(self._violations)
        return payload
