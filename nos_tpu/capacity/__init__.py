from nos_tpu.capacity.ledger import (  # noqa: F401
    BUCKET_AUTOSCALER,
    BUCKET_NO_DEMAND,
    BUCKET_PENDING,
    BUCKET_RECONFIG,
    BUCKET_RESERVED,
    CapacityLedger,
    cluster_fragmentation_index,
    fragmentation_from_annotations,
    largest_profile_chips,
)
