"""Cluster capacity ledger: live, incremental chip-seconds accounting.

ROADMAP item 2 sets utilization targets (idle-with-pending-demand < 3%,
8-chip gang p50 wait < 1s) that until now existed only as post-hoc
computations inside bench.py. This module is the live meter: a
:class:`CapacityLedger` drains the same rv-ordered store deltas the
flight recorder and the IncrementalSnapshotMaintainer consume (one watch
stream, another read view) and integrates chip-seconds over the wall
time between control-cycle observations.

Accounting model
----------------
Every ``observe(now)`` call closes the interval ``[last_ts, now)``. The
interval is integrated against the state the ledger held at its previous
revision watermark — events drained *during* the interval describe
transitions that become visible at the *end* of it, exactly the view a
control cycle has. Per node (iterated in sorted-name order so float
accumulation is bit-reproducible on replay):

- ``busy``   = chips of pods bound to the node (request arithmetic via
  :func:`nos_tpu.util.resources.tpu_chips_in`), capped at capacity;
- ``idle``   = capacity - busy, attributed to one bucket:
  * ``reconfig``            — the node's spec plan differs from its
    reported status plan (a partitioning plan is in flight);
  * ``reserved-by-gang``    — the node carries a board reservation
    annotation for a pending gang;
  * ``autoscaler-grace``    — the node is held by the model autoscaler's
    cold-start grace reservation after a scale-to-zero (a deliberate
    wake-latency trade, not scheduling waste);
  * ``pending-unschedulable`` — otherwise, up to the cluster's unbound
    pending TPU demand (``min(idle, pending_chips)``, the same coverage
    rule bench.py's post-hoc attribution uses), labeled with the
    dominant carve-failure reason joined from the planner's
    ``last_unserved`` ledger;
  * ``no-demand``           — the remainder.

The ledger additionally tracks a per-node fragmentation index (1 -
largest-carveable-slice / free-chips, from the status annotations and
the accelerator's slice shapes) and a cluster index (1 - best single
carve anywhere / min(free total, largest known profile) — NOT the
free-weighted mean of node indices, which reads 0.0 exactly when every
node has decayed to slivers; see :func:`cluster_fragmentation_index`),
per-gang wait clocks (arrival →
first-feasible → bound) feeding ``nos_tpu_gang_wait_seconds``, and
per-namespace quota borrow/starvation derived from ElasticQuota objects.

Determinism & verification
--------------------------
Each integrating ``observe`` appends a ``capacity.observe`` record to
the flight recorder (watermark revision, observation timestamp, pending
reason, cumulative totals). Replay rebuilds a shadow ledger over the
replayed store and re-runs the same observations from the recorded
timestamps — totals must match bit-for-bit (zero drift). Live, the
InvariantAuditor's ``capacity_ledger`` check calls :meth:`self_check`,
which recomputes the instantaneous state from scratch off the store and
diffs it against the incrementally-maintained state; the chaos
``ledger-consistent`` oracle runs the same check after every burst.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.tpu.known import KNOWN_ACCELERATORS
from nos_tpu.tpu.topology import topology_chips
from nos_tpu.util import metrics as m
from nos_tpu.util import resources as res

# Idle-attribution buckets. Low-cardinality by construction: the only
# free-form label is the pending reason, normalized to its prefix.
BUCKET_NO_DEMAND = "no-demand"
BUCKET_PENDING = "pending-unschedulable"
BUCKET_RECONFIG = "reconfig"
BUCKET_RESERVED = "reserved-by-gang"
BUCKET_AUTOSCALER = "autoscaler-grace"
IDLE_BUCKETS = (
    BUCKET_NO_DEMAND,
    BUCKET_PENDING,
    BUCKET_RECONFIG,
    BUCKET_RESERVED,
    BUCKET_AUTOSCALER,
)

# Store kinds the ledger's delta view understands (same set the
# IncrementalSnapshotMaintainer watches).
WATCH_KINDS = ("ElasticQuota", "Node", "Pod")

# Annotation the gang reservation plugin stamps on held nodes.
_RESERVED_FOR = annot.PREFIX + "reserved-for"

# Pending-demand label when no carve-failure reason is known (demand
# exists but the planner has not reported why it is unserved).
_REASON_QUEUED = "queued"

# Completed gang wait entries kept for /debug/capacity.
_RECENT_GANGS = 64

_UNSET = object()


def _reason_prefix(reason: str) -> str:
    """Normalize a carve-failure message to its low-cardinality prefix
    (the part before ':'), matching the unschedulable metric's scheme."""
    return reason.split(":", 1)[0].strip() or _REASON_QUEUED


def dominant_unserved_reason(unserved: Dict[str, str]) -> Optional[str]:
    """The most common normalized reason in a pod→reason map. Sorted by
    (count desc, reason asc) explicitly — never dict insertion order —
    so the label is deterministic for any map with tied counts (forecast
    records and replay comparisons inherit this field)."""
    counts: Dict[str, int] = {}
    for reason in unserved.values():
        key = _reason_prefix(reason)
        counts[key] = counts.get(key, 0) + 1
    if not counts:
        return None
    return min(counts, key=lambda k: (-counts[k], k))


def fragmentation_from_annotations(
    annotations: Dict[str, str], accelerator: str
) -> Tuple[float, int, int]:
    """(fragmentation index, largest carveable chips, free chips) for a
    node's reported slice state.

    Free chips are summed from the ``free`` status annotations; the
    largest carveable slice is the biggest profile in the accelerator's
    slice shapes that fits inside a single board's free chips (carving
    never crosses boards). Index = 1 - largest/free; 0 when nothing is
    free (a full node is busy, not fragmented)."""
    _, status = annot.parse_node_annotations(annotations)
    free_by_board: Dict[int, int] = {}
    for entry in status:
        if entry.status == annot.STATUS_FREE and "x" in entry.profile:
            chips = topology_chips(entry.profile) * entry.quantity
            free_by_board[entry.board_index] = (
                free_by_board.get(entry.board_index, 0) + chips
            )
    free_total = sum(free_by_board.values())
    if free_total <= 0:
        return 0.0, 0, 0
    spec = KNOWN_ACCELERATORS.get(accelerator)
    shape_chips = (
        sorted(topology_chips(s) for s in spec.slice_shapes) if spec else []
    )
    largest = 0
    for board_free in free_by_board.values():
        for chips in shape_chips:
            if chips <= board_free and chips > largest:
                largest = chips
    return 1.0 - largest / free_total, largest, free_total


def largest_profile_chips(accelerator: str) -> int:
    """The biggest carveable slice (in chips) the accelerator's shape
    table admits — the most any single workload could ask of one node."""
    spec = KNOWN_ACCELERATORS.get(accelerator)
    if not spec:
        return 0
    return max(topology_chips(s) for s in spec.slice_shapes)


def cluster_fragmentation_index(
    free_chips_total: float,
    largest_free_slice: float,
    largest_profile: float,
) -> float:
    """Cluster-level fragmentation: how far the best single carve
    anywhere falls short of the largest slice a workload could ask for,
    bounded by what is actually free.

    The free-chip-weighted mean of per-node indices is NOT this number:
    it reads 0.0 exactly when every node has decayed to slivers (each
    node's largest carve equals its own tiny free pool — e.g. 1487 free
    chips cluster-wide whose best carve is a 1x2), which is the most
    fragmented state a cluster can reach, not the least. This index
    instead compares the single best carve to
    ``min(free total, largest known profile)``: 0.0 when nothing is free
    or the biggest askable slice still fits somewhere, approaching 1.0
    as free capacity becomes uncarveable."""
    if free_chips_total <= 0:
        return 0.0
    askable = free_chips_total
    if largest_profile > 0:
        askable = min(askable, largest_profile)
    if askable <= 0:
        return 0.0
    return max(0.0, 1.0 - largest_free_slice / askable)


def _pod_chips(pod: Any) -> int:
    return res.tpu_chips_in(res.compute_pod_request(pod))


def _quota_chips(resource_list: Dict[str, Any]) -> int:
    """Chips a quota bound amounts to: the synthetic aggregate when the
    quota is expressed in it, the extended-resource arithmetic otherwise."""
    if constants.RESOURCE_TPU_CHIPS in resource_list:
        return int(resource_list[constants.RESOURCE_TPU_CHIPS])
    return res.tpu_chips_in(resource_list)


class _NodeState:
    """Instantaneous per-node facts the integration step reads."""

    __slots__ = (
        "total_chips",
        "pool",
        "accelerator",
        "frozen",
        "reserved",
        "autoscaler_grace",
        "frag_index",
        "largest_free_slice",
        "free_chips",
        "used_profiles",
    )

    def __init__(self, node: Any, total_chips: int) -> None:
        meta = node.metadata
        self.total_chips = total_chips
        self.pool = meta.labels.get(labels.PARTITIONING_LABEL, "")
        self.accelerator = meta.labels.get(labels.GKE_TPU_ACCELERATOR_LABEL, "")
        ann = meta.annotations
        spec_plan = ann.get(annot.SPEC_PARTITIONING_PLAN)
        self.frozen = bool(spec_plan) and spec_plan != ann.get(
            annot.STATUS_PARTITIONING_PLAN
        )
        self.reserved = _RESERVED_FOR in ann
        # Cold-start grace hold stamped by the model autoscaler on
        # scale-to-zero: idle here is a deliberate wake-latency trade,
        # not scheduling inefficiency, and must not read as no-demand.
        self.autoscaler_grace = annot.AUTOSCALER_RESERVED in ann
        self.frag_index, self.largest_free_slice, self.free_chips = (
            fragmentation_from_annotations(ann, self.accelerator)
        )
        _, status = annot.parse_node_annotations(ann)
        used: Dict[str, int] = {}
        for entry in status:
            if entry.status == annot.STATUS_USED and "x" in entry.profile:
                used[entry.profile] = (
                    used.get(entry.profile, 0)
                    + topology_chips(entry.profile) * entry.quantity
                )
        self.used_profiles = used

    def canonical(self) -> tuple:
        return (
            self.total_chips,
            self.pool,
            self.accelerator,
            self.frozen,
            self.reserved,
            self.autoscaler_grace,
            round(self.frag_index, 9),
            self.largest_free_slice,
            self.free_chips,
            tuple(sorted(self.used_profiles.items())),
        )


class CapacityLedger:
    """Incremental time-weighted chip-seconds accounting over a KubeStore.

    Thread-safe: ``observe`` / gang clocks / ``debug_payload`` may be
    called from different controller threads; all state is guarded by one
    lock. The store's watch queue is the only cross-thread hand-off.

    ``metrics`` turns Prometheus export off for replay shadow ledgers so
    a replayed run never pollutes the live registry.
    """

    def __init__(
        self,
        store,
        flight_recorder=None,
        metrics: bool = True,
        node_top_k: int = 0,
    ) -> None:
        self.store = store
        self.flight = flight_recorder
        self._metrics = metrics
        # Tiered exposition: 0 exports every node's gauges (small-world
        # behavior); K > 0 keeps exact per-pool rollups plus only the K
        # worst-offender nodes (most idle chips, then most fragmented) —
        # the governor's answer to 300k node series at 100k nodes.
        self.node_top_k = node_top_k
        self._lock = threading.Lock()
        self._queue = (
            store.watch(set(WATCH_KINDS), name="capacity-ledger")
            if store is not None
            else None
        )
        self._buffer: List[Any] = []
        # Instantaneous state at the current revision watermark.
        self._nodes: Dict[str, _NodeState] = {}
        self._bound: Dict[str, Tuple[str, int, str]] = {}  # pod -> (node, chips, ns)
        self._pending: Dict[str, Tuple[int, str]] = {}  # pod -> (chips, ns)
        self._quotas: Dict[str, Tuple[str, int, int, int]] = {}  # key -> (ns,min,max,used)
        self._reason: Optional[str] = None
        self._unserved_sample: Dict[str, str] = {}
        self._last_ts: Optional[float] = None
        self._first_ts: Optional[float] = None
        self._revision = 0
        self._last_trace_id = ""
        # Cumulative chip-second integrals.
        self.total_chip_seconds = 0.0
        self.busy_chip_seconds = 0.0
        self.idle_chip_seconds: Dict[str, float] = {b: 0.0 for b in IDLE_BUCKETS}
        self.pending_reason_seconds: Dict[str, float] = {}
        self.by_node: Dict[str, Dict[str, float]] = {}
        self.by_pool: Dict[str, Dict[str, float]] = {}
        self.by_namespace: Dict[str, float] = {}
        self.by_profile: Dict[str, float] = {}
        self.observes = 0
        # Gang wait clocks (live-only; excluded from replay drift).
        self._gangs: Dict[str, Dict[str, float]] = {}
        self._recent_gangs: deque = deque(maxlen=_RECENT_GANGS)
        # Live gang membership derived from pod deltas, so a gang whose
        # every member is deleted before binding drops its wait clock —
        # a same-named re-arrival must start a fresh clock, not inherit
        # a stale one (forecast accuracy joins against these waits).
        self._gang_members: Dict[str, set] = {}
        self._pod_gang: Dict[str, str] = {}
        # Fired on gang-bound with (gang, now, wait_seconds), outside the
        # ledger lock (the forecaster joins forecast accuracy here).
        self._gang_bound_listeners: List[Any] = []
        # Measured node reconfig (re-carve actuation) latency: frozen
        # rising/falling edges observed in the delta stream, stamped with
        # the observation clock so replay reproduces the same stats.
        self._apply_now: Optional[float] = None
        self._reconfig_started: Dict[str, float] = {}
        self.reconfig_count = 0
        self.reconfig_seconds_total = 0.0
        # Node/pool names with exported labeled gauges (delete-on-vanish:
        # the registry supports child removal, so stale series disappear
        # from exposition instead of lingering at zero).
        self._exported_nodes: set = set()
        self._exported_pools: set = set()
        # Heartbeat: the control loops only observe when they run (the
        # partitioner on plan cycles), so a quiet steady-state cluster
        # would stop accruing chip-seconds without a periodic tick.
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        # Wall-clock seam: everything inside the ledger that reads "now"
        # for an observation goes through this, so the chaos harness can
        # skew wall time against the monotonic clock (the clock-skew
        # fault) without monkeypatching time.time for the whole process.
        self.wall_clock = time.time
        # Health-timeline leak watch: gang wait clocks are pruned when a
        # gang binds or loses its last member — unpruned clocks are the
        # canonical aging leak this map could grow.
        from nos_tpu.timeline.sizes import SIZES

        SIZES.register("capacity.gang_clocks", lambda: len(self._gangs))

    # ---------------------------------------------------------- heartbeat

    def start_heartbeat(self, interval_seconds: float = 5.0) -> None:
        """Observe on a timer so integrals keep accruing while the control
        loops idle. Heartbeat observes are recorded like any other — an
        unrecorded watermark advance would make every later recorded total
        unreproducible on replay."""
        if self._hb_thread is not None:
            return
        self._hb_stop.clear()
        from nos_tpu.timeline.watchdog import WATCHDOG
        from nos_tpu.util.profiling import PROFILER

        WATCHDOG.register(
            "capacity-heartbeat",
            periodic=True,
            thread_name="capacity-heartbeat",
            counter_fn=lambda: self.observes,
        )

        def loop() -> None:
            PROFILER.register_thread(name="capacity-heartbeat")
            try:
                while not self._hb_stop.wait(interval_seconds):
                    WATCHDOG.beat("capacity-heartbeat")
                    self.observe(self.wall_clock())
            finally:
                PROFILER.unregister_thread()

        self._hb_thread = threading.Thread(
            target=loop, name="capacity-heartbeat", daemon=True
        )
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        if self._hb_thread is None:
            return
        self._hb_stop.set()
        self._hb_thread.join(timeout=5.0)
        self._hb_thread = None
        from nos_tpu.timeline.watchdog import WATCHDOG

        WATCHDOG.unregister("capacity-heartbeat")

    # ------------------------------------------------------------ observe

    def observe(
        self,
        now: float,
        unserved: Optional[Dict[str, str]] = None,
        reason: Any = _UNSET,
        trace_id: str = "",
        record: bool = True,
    ) -> None:
        """Close the interval since the previous observation and roll the
        watermark forward.

        ``unserved`` is the planner's pod→reason carve-failure map; the
        dominant normalized reason labels pending-idle time from here
        until the next observation. ``reason`` overrides that computation
        directly (the replay path, which replays the recorded choice).
        """
        with self._lock:
            watermark = self.store.revision
            self._integrate(now)
            # Deltas drained below are stamped with this observation's
            # clock (reconfig edge timing): deterministic on replay,
            # which re-observes with the recorded ``now``.
            self._apply_now = now
            self._drain_apply(watermark)
            if reason is not _UNSET:
                self._reason = reason
            elif unserved is not None:
                self._reason = dominant_unserved_reason(unserved)
                self._unserved_sample = {
                    k: unserved[k] for k in sorted(unserved)[:32]
                }
                if not unserved:
                    self._unserved_sample = {}
            if trace_id:
                self._last_trace_id = trace_id
            self._last_ts = now
            if self._first_ts is None:
                self._first_ts = now
            self._revision = watermark
            self.observes += 1
            if self._metrics:
                self._export_gauges()
            totals = self._totals()
            reason_out = self._reason
        if record and self.flight is not None:
            self.flight.record_capacity(
                revision=watermark,
                now=now,
                reason=reason_out,
                trace_id=trace_id,
                totals=totals,
            )

    def _integrate(self, now: float) -> None:
        if self._last_ts is None:
            return
        dt = now - self._last_ts
        if dt <= 0 or not self._nodes:
            return
        bound_by_node: Dict[str, int] = {}
        busy_by_ns: Dict[str, int] = {}
        for key in sorted(self._bound):
            node_name, chips, ns = self._bound[key]
            if node_name not in self._nodes:
                continue
            bound_by_node[node_name] = bound_by_node.get(node_name, 0) + chips
            busy_by_ns[ns] = busy_by_ns.get(ns, 0) + chips
        pending_chips = sum(chips for chips, _ in self._pending.values())
        available_idle = 0
        for name in sorted(self._nodes):
            st = self._nodes[name]
            busy = min(st.total_chips, bound_by_node.get(name, 0))
            idle = st.total_chips - busy
            self.total_chip_seconds += st.total_chips * dt
            self.busy_chip_seconds += busy * dt
            node_acc = self.by_node.setdefault(name, {"total": 0.0, "busy": 0.0})
            node_acc["total"] += st.total_chips * dt
            node_acc["busy"] += busy * dt
            pool_acc = self.by_pool.setdefault(st.pool, {"total": 0.0, "busy": 0.0})
            pool_acc["total"] += st.total_chips * dt
            pool_acc["busy"] += busy * dt
            if st.frozen:
                self.idle_chip_seconds[BUCKET_RECONFIG] += idle * dt
            elif st.reserved:
                self.idle_chip_seconds[BUCKET_RESERVED] += idle * dt
            elif st.autoscaler_grace:
                self.idle_chip_seconds[BUCKET_AUTOSCALER] += idle * dt
            else:
                available_idle += idle
            for profile in sorted(st.used_profiles):
                self.by_profile[profile] = (
                    self.by_profile.get(profile, 0.0) + st.used_profiles[profile] * dt
                )
        for ns in sorted(busy_by_ns):
            self.by_namespace[ns] = self.by_namespace.get(ns, 0.0) + busy_by_ns[ns] * dt
        # Idle on schedulable nodes is "scheduling inefficiency" only up
        # to the demand that could have used it (bench.py's coverage rule).
        covered = float(min(available_idle, pending_chips))
        self.idle_chip_seconds[BUCKET_PENDING] += covered * dt
        self.idle_chip_seconds[BUCKET_NO_DEMAND] += (available_idle - covered) * dt
        if covered > 0:
            reason = self._reason or _REASON_QUEUED
            self.pending_reason_seconds[reason] = (
                self.pending_reason_seconds.get(reason, 0.0) + covered * dt
            )
        if self._metrics:
            c = m.CAPACITY_CHIP_SECONDS
            c.labels(state="busy", reason="").inc(
                sum(
                    min(self._nodes[n].total_chips, bound_by_node.get(n, 0))
                    for n in self._nodes
                )
                * dt
            )
            for name in sorted(self._nodes):
                st = self._nodes[name]
                idle = st.total_chips - min(
                    st.total_chips, bound_by_node.get(name, 0)
                )
                if st.frozen:
                    c.labels(state=BUCKET_RECONFIG, reason="").inc(idle * dt)
                elif st.reserved:
                    c.labels(state=BUCKET_RESERVED, reason="").inc(idle * dt)
                elif st.autoscaler_grace:
                    c.labels(state=BUCKET_AUTOSCALER, reason="").inc(idle * dt)
            if covered > 0:
                c.labels(
                    state=BUCKET_PENDING, reason=self._reason or _REASON_QUEUED
                ).inc(covered * dt)
            c.labels(state=BUCKET_NO_DEMAND, reason="").inc(
                (available_idle - covered) * dt
            )

    # ------------------------------------------------------------- deltas

    def _drain_apply(self, watermark: int) -> None:
        if self._queue is not None:
            while True:
                try:
                    self._buffer.append(self._queue.get_nowait())
                except queue.Empty:
                    break
        keep: List[Any] = []
        for event in self._buffer:
            revision = event.revision or event.object.metadata.resource_version
            if revision <= watermark:
                self._apply_event(event)
            else:
                keep.append(event)
        self._buffer = keep

    def _apply_event(self, event: Any) -> None:
        kind = event.object.kind
        if kind == "Node":
            self._apply_node(event)
        elif kind == "Pod":
            self._apply_pod(event)
        elif kind == "ElasticQuota":
            self._apply_quota(event)

    def _apply_node(self, event: Any) -> None:
        node = event.object
        name = node.metadata.name
        if event.type == "DELETED":
            self._reconfig_started.pop(name, None)
            if self._nodes.pop(name, None) is not None and self._metrics:
                self._drop_node_gauges(name)
            return
        total = int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
        if total <= 0:
            self._reconfig_started.pop(name, None)
            if self._nodes.pop(name, None) is not None and self._metrics:
                self._drop_node_gauges(name)
            return
        old = self._nodes.get(name)
        state = _NodeState(node, total)
        self._note_reconfig_edge(name, old, state)
        self._nodes[name] = state

    def _note_reconfig_edge(
        self, name: str, old: Optional[_NodeState], new: _NodeState
    ) -> None:
        """frozen False→True starts a reconfig; True→False completes it.
        The elapsed observation-clock time feeds the measured reconfig
        rate the forecaster prices re-carve ETAs with."""
        was_frozen = old is not None and old.frozen
        if new.frozen and not was_frozen:
            if self._apply_now is not None:
                self._reconfig_started[name] = self._apply_now
        elif was_frozen and not new.frozen:
            started = self._reconfig_started.pop(name, None)
            if started is not None and self._apply_now is not None:
                self.reconfig_count += 1
                self.reconfig_seconds_total += max(
                    0.0, self._apply_now - started
                )

    def _apply_pod(self, event: Any) -> None:
        pod = event.object
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        self._bound.pop(key, None)
        self._pending.pop(key, None)
        self._track_gang_membership(key, pod, event.type)
        if event.type == "DELETED":
            return
        chips = _pod_chips(pod)
        if chips <= 0:
            return
        phase = pod.status.phase
        if pod.spec.node_name and phase in ("Pending", "Running"):
            self._bound[key] = (pod.spec.node_name, chips, pod.metadata.namespace)
        elif phase == "Pending":
            self._pending[key] = (chips, pod.metadata.namespace)

    def _track_gang_membership(
        self, key: str, pod: Any, event_type: str
    ) -> None:
        """Keep ``_gang_members`` consistent with the pod stream, and
        drop an unbound gang's wait clock the moment its last member
        disappears — deleted-before-bound and preempt-then-resubmit must
        restart the clock instead of inheriting a stale arrival."""
        gang_key = None
        if event_type != "DELETED":
            # Lazy import: scheduler.plugins.gang pulls the KubeStore
            # stack (same pattern as the planner).
            from nos_tpu.scheduler.plugins.gang import gang_of

            gang = gang_of(pod)
            gang_key = gang[0] if gang else None
        prev = self._pod_gang.get(key)
        if prev == gang_key:
            return
        if prev is not None:
            members = self._gang_members.get(prev)
            if members is not None:
                members.discard(key)
                if not members:
                    del self._gang_members[prev]
                    self._gangs.pop(prev, None)
        if gang_key is None:
            self._pod_gang.pop(key, None)
        else:
            self._pod_gang[key] = gang_key
            self._gang_members.setdefault(gang_key, set()).add(key)

    def _apply_quota(self, event: Any) -> None:
        quota = event.object
        key = f"{quota.metadata.namespace}/{quota.metadata.name}"
        if event.type == "DELETED":
            self._quotas.pop(key, None)
            return
        self._quotas[key] = (
            quota.metadata.namespace,
            _quota_chips(quota.spec.min),
            _quota_chips(quota.spec.max),
            _quota_chips(quota.status.used),
        )

    # -------------------------------------------------------- gang clocks

    def note_gang_arrival(self, gang: str, now: float) -> None:
        with self._lock:
            self._gangs.setdefault(gang, {"arrival": now})

    def note_gang_feasible(self, gang: str, now: float) -> None:
        with self._lock:
            clock = self._gangs.get(gang)
            if clock is None or "feasible" in clock:
                return
            clock["feasible"] = now
            wait = max(0.0, now - clock["arrival"])
        if self._metrics:
            m.GANG_WAIT_SECONDS.labels(stage="first_feasible").observe(wait)

    def note_gang_bound(self, gang: str, now: float) -> None:
        with self._lock:
            clock = self._gangs.pop(gang, None)
            if clock is None:
                return
            clock["bound"] = now
            wait = max(0.0, now - clock["arrival"])
            self._recent_gangs.append(
                {
                    "gang": gang,
                    "wait_seconds": round(wait, 6),
                    "feasible_after": (
                        round(clock["feasible"] - clock["arrival"], 6)
                        if "feasible" in clock
                        else None
                    ),
                }
            )
            listeners = list(self._gang_bound_listeners)
        if self._metrics:
            m.GANG_WAIT_SECONDS.labels(stage="bound").observe(wait)
        # Outside the lock: a listener (the forecast accuracy join) may
        # itself read ledger state or block on I/O.
        for listener in listeners:
            try:
                listener(gang, now, wait)
            except Exception:
                logging.getLogger("nos_tpu.capacity").exception(
                    "gang-bound listener failed for %s", gang
                )

    def add_gang_bound_listener(self, listener: Any) -> None:
        """Register ``listener(gang, now, wait_seconds)``, invoked after
        every gang-bound observation, outside the ledger lock."""
        with self._lock:
            self._gang_bound_listeners.append(listener)

    def drop_gang(self, gang: str) -> None:
        """Forget a gang's clock (gang timeout: it will never bind)."""
        with self._lock:
            self._gangs.pop(gang, None)

    def gang_clocks(self) -> Dict[str, Dict[str, float]]:
        """Snapshot of the live gang wait clocks (gang -> stamp map) —
        the forecaster's wait ages and ETA normalizers."""
        with self._lock:
            return {gang: dict(clock) for gang, clock in self._gangs.items()}

    # ------------------------------------------------------------ exports

    def _totals(self) -> Dict[str, Any]:
        """Cumulative integrals, the replay drift-comparison payload.
        Plain floats: json round-trips IEEE doubles exactly, so recorded
        and recomputed totals can be compared bit-for-bit."""
        return {
            "total": self.total_chip_seconds,
            "busy": self.busy_chip_seconds,
            "idle": dict(self.idle_chip_seconds),
            "reasons": dict(self.pending_reason_seconds),
            "pools": {k: dict(v) for k, v in self.by_pool.items()},
            "namespaces": dict(self.by_namespace),
        }

    def totals(self) -> Dict[str, Any]:
        with self._lock:
            return self._totals()

    def mean_reconfig_seconds(self, default: float = 0.5) -> float:
        """Measured mean node re-carve latency (frozen edge to edge);
        ``default`` until the first completed reconfig is observed. Kept
        out of ``_totals()`` — the replay drift payload must not grow."""
        with self._lock:
            if self.reconfig_count <= 0:
                return default
            return self.reconfig_seconds_total / self.reconfig_count

    def reconfig_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "count": self.reconfig_count,
                "seconds_total": self.reconfig_seconds_total,
                "in_flight": sorted(self._reconfig_started),
            }

    def utilization(self) -> float:
        with self._lock:
            if self.total_chip_seconds <= 0:
                return 0.0
            return self.busy_chip_seconds / self.total_chip_seconds

    def idle_pending_fraction(self) -> float:
        with self._lock:
            if self.total_chip_seconds <= 0:
                return 0.0
            return self.idle_chip_seconds[BUCKET_PENDING] / self.total_chip_seconds

    def _export_gauges(self) -> None:
        if self.total_chip_seconds > 0:
            m.CAPACITY_UTILIZATION.set(
                self.busy_chip_seconds / self.total_chip_seconds
            )
            m.CAPACITY_IDLE_PENDING_FRACTION.set(
                self.idle_chip_seconds[BUCKET_PENDING] / self.total_chip_seconds
            )
        bound_by_node: Dict[str, int] = {}
        for node_name, chips, _ in self._bound.values():
            bound_by_node[node_name] = bound_by_node.get(node_name, 0) + chips
        free_total = largest_free = largest_profile = 0.0
        pool_rollup: Dict[str, Dict[str, int]] = {}
        offenders: List[Tuple[float, float, str]] = []
        for name in sorted(self._nodes):
            st = self._nodes[name]
            used = min(st.total_chips, bound_by_node.get(name, 0))
            roll = pool_rollup.setdefault(
                st.pool or "", {"total": 0, "used": 0, "free": 0}
            )
            roll["total"] += st.total_chips
            roll["used"] += used
            roll["free"] += st.total_chips - used
            offenders.append((-(st.total_chips - used), -st.frag_index, name))
            free_total += st.free_chips
            largest_free = max(largest_free, st.largest_free_slice)
            largest_profile = max(
                largest_profile, largest_profile_chips(st.accelerator)
            )
        # Tier 1: exact per-pool rollups, always. Vanished pools drop
        # their series (exposition must not carry ghost pools).
        for pool in sorted(pool_rollup):
            for state, value in sorted(pool_rollup[pool].items()):
                m.CAPACITY_POOL_CHIPS.labels(pool=pool, state=state).set(value)
            self._exported_pools.add(pool)
        for pool in sorted(self._exported_pools - set(pool_rollup)):
            for state in ("total", "used", "free"):
                m.CAPACITY_POOL_CHIPS.remove(pool=pool, state=state)
            self._exported_pools.discard(pool)
        # Tier 2: per-node gauges — every node at node_top_k=0, else only
        # the K worst offenders (most idle chips, then most fragmented,
        # then name: a deterministic total order, so the exported set is
        # a pure function of ledger state).
        if self.node_top_k > 0:
            offenders.sort()
            selected = {name for _, _, name in offenders[: self.node_top_k]}
        else:
            selected = set(self._nodes)
        for name in sorted(self._exported_nodes - selected):
            self._drop_node_gauges(name)
        for name in sorted(selected):
            st = self._nodes[name]
            used = min(st.total_chips, bound_by_node.get(name, 0))
            m.CAPACITY_NODE_CHIPS.labels(node=name, state="total").set(st.total_chips)
            m.CAPACITY_NODE_CHIPS.labels(node=name, state="used").set(used)
            m.CAPACITY_NODE_CHIPS.labels(node=name, state="free").set(
                st.total_chips - used
            )
            m.NODE_FRAGMENTATION.labels(node=name).set(st.frag_index)
            self._exported_nodes.add(name)
        m.CLUSTER_FRAGMENTATION.set(
            cluster_fragmentation_index(free_total, largest_free, largest_profile)
        )
        starved_ok = {
            ns for _, ns in self._pending.values()
        }  # namespaces with queued demand
        for key in sorted(self._quotas):
            ns, min_chips, _, used = self._quotas[key]
            m.QUOTA_BORROWED_CHIPS.labels(namespace=ns).set(max(0, used - min_chips))
            m.QUOTA_STARVED_CHIPS.labels(namespace=ns).set(
                max(0, min_chips - used) if ns in starved_ok else 0
            )

    def _drop_node_gauges(self, name: str) -> None:
        """A deleted (or tiered-out) node's labeled gauges would otherwise
        report its last live values forever; delete the series so they
        vanish from exposition and free their governor budget slots."""
        if name not in self._exported_nodes:
            return
        for state in ("total", "used", "free"):
            m.CAPACITY_NODE_CHIPS.remove(node=name, state=state)
        m.NODE_FRAGMENTATION.remove(node=name)
        self._exported_nodes.discard(name)

    # ---------------------------------------------------------- debugging

    def debug_payload(
        self, pool: str = "", limit: int = 0, cursor: str = ""
    ) -> Dict[str, Any]:
        """The /debug/capacity document: cluster rollup, per-node detail,
        quota posture, gang wait clocks, and links into the other debug
        surfaces (explain/traces/record) for cross-navigation.

        ``pool`` filters the per-node section; ``limit``/``cursor`` page
        it (cursor = last node name of the previous page) so the HTTP
        layer never materializes 100k node records in one response. The
        cluster rollup always covers every node regardless of paging.
        Defaults reproduce the full pre-paging document. ``pending_pods``
        is capped at the same ``limit`` — it is the other O(cluster) list.
        """
        with self._lock:
            bound_by_node: Dict[str, int] = {}
            for node_name, chips, _ in self._bound.values():
                bound_by_node[node_name] = bound_by_node.get(node_name, 0) + chips
            total_now = sum(st.total_chips for st in self._nodes.values())
            used_now = sum(
                min(self._nodes[n].total_chips, c)
                for n, c in bound_by_node.items()
                if n in self._nodes
            )
            pending_now = sum(chips for chips, _ in self._pending.values())
            window = (
                (self._last_ts - self._first_ts)
                if self._last_ts is not None and self._first_ts is not None
                else 0.0
            )
            denom = self.total_chip_seconds or 1.0
            nodes = {}
            free_frag = largest_free = largest_profile = 0.0
            for name in sorted(self._nodes):
                st = self._nodes[name]
                free_frag += st.free_chips
                largest_free = max(largest_free, st.largest_free_slice)
                largest_profile = max(
                    largest_profile, largest_profile_chips(st.accelerator)
                )
            names = [
                n
                for n in sorted(self._nodes)
                if not pool or self._nodes[n].pool == pool
            ]
            from nos_tpu.obsplane.streaming import paginate

            page_names, next_cursor = paginate(names, limit, cursor)
            for name in page_names:
                st = self._nodes[name]
                used = min(st.total_chips, bound_by_node.get(name, 0))
                acc = self.by_node.get(name, {"total": 0.0, "busy": 0.0})
                nodes[name] = {
                    "pool": st.pool,
                    "accelerator": st.accelerator,
                    "total_chips": st.total_chips,
                    "used_chips": used,
                    "free_chips": st.total_chips - used,
                    "frozen": st.frozen,
                    "reserved": st.reserved,
                    "fragmentation": round(st.frag_index, 6),
                    "largest_free_slice_chips": st.largest_free_slice,
                    "busy_chip_seconds": acc["busy"],
                    "total_chip_seconds": acc["total"],
                    "utilization": (
                        acc["busy"] / acc["total"] if acc["total"] else 0.0
                    ),
                }
            pending_ns = {ns for _, ns in self._pending.values()}
            quotas = {}
            for key in sorted(self._quotas):
                ns, min_chips, max_chips, used = self._quotas[key]
                quotas[key] = {
                    "namespace": ns,
                    "min_chips": min_chips,
                    "max_chips": max_chips,
                    "used_chips": used,
                    "borrowed_chips": max(0, used - min_chips),
                    "starved_chips": (
                        max(0, min_chips - used) if ns in pending_ns else 0
                    ),
                }
            pending_keys = sorted(self._pending)
            if limit and limit > 0:
                pending_keys = pending_keys[:limit]
            pending_pods = [
                {
                    "pod": key,
                    "chips": self._pending[key][0],
                    "namespace": self._pending[key][1],
                    "reason": self._unserved_sample.get(key),
                    "links": {"explain": f"/debug/explain?pod={key}"},
                }
                for key in pending_keys
            ]
            return {
                "revision": self._revision,
                "ts": self._last_ts,
                "window_seconds": window,
                "observes": self.observes,
                "cluster": {
                    "total_chips": total_now,
                    "used_chips": used_now,
                    "free_chips": total_now - used_now,
                    "pending_chips": pending_now,
                    "utilization": self.busy_chip_seconds / denom,
                    "idle_with_pending_demand": (
                        self.idle_chip_seconds[BUCKET_PENDING] / denom
                    ),
                    "fragmentation": cluster_fragmentation_index(
                        free_frag, largest_free, largest_profile
                    ),
                    "largest_free_slice_chips": largest_free,
                    "chip_seconds": {
                        "total": self.total_chip_seconds,
                        "busy": self.busy_chip_seconds,
                        "idle": dict(self.idle_chip_seconds),
                        "pending_reasons": dict(self.pending_reason_seconds),
                    },
                },
                "pools": {k: dict(v) for k, v in sorted(self.by_pool.items())},
                "namespaces": dict(sorted(self.by_namespace.items())),
                "profiles": dict(sorted(self.by_profile.items())),
                "nodes": nodes,
                "quotas": quotas,
                "pending_pods": pending_pods,
                "gangs": {
                    "waiting": {
                        gang: dict(clock)
                        for gang, clock in sorted(self._gangs.items())
                    },
                    "recent": list(self._recent_gangs),
                },
                "links": {
                    "trace_id": self._last_trace_id,
                    "traces": "/debug/traces",
                    "record": "/debug/record",
                    "vars": "/debug/vars",
                },
                "page": {
                    "pool": pool,
                    "limit": limit,
                    "cursor": cursor,
                    "next_cursor": next_cursor,
                    "total_nodes": len(names),
                },
            }

    def debug_stream(self, pool: str = ""):
        """JSONL generator for ``/debug/capacity?format=jsonl``: a cluster
        header record, then one record per node, then quotas — each line
        O(1). State is snapshotted under the lock once; _NodeState objects
        are replaced (never mutated) on apply, so iterating the captured
        references outside the lock is safe and a slow HTTP client never
        holds up ``observe``."""
        with self._lock:
            bound_by_node: Dict[str, int] = {}
            for node_name, chips, _ in self._bound.values():
                bound_by_node[node_name] = bound_by_node.get(node_name, 0) + chips
            items = [
                (name, self._nodes[name])
                for name in sorted(self._nodes)
                if not pool or self._nodes[name].pool == pool
            ]
            header = {
                "record": "cluster",
                "revision": self._revision,
                "ts": self._last_ts,
                "observes": self.observes,
                "nodes": len(items),
                "pool": pool,
                "total_chips": sum(st.total_chips for _, st in items),
            }
            quotas = dict(self._quotas)
        yield header
        for name, st in items:
            used = min(st.total_chips, bound_by_node.get(name, 0))
            yield {
                "record": "node",
                "name": name,
                "pool": st.pool,
                "accelerator": st.accelerator,
                "total_chips": st.total_chips,
                "used_chips": used,
                "free_chips": st.total_chips - used,
                "frozen": st.frozen,
                "reserved": st.reserved,
                "fragmentation": round(st.frag_index, 6),
            }
        for key in sorted(quotas):
            ns, min_chips, max_chips, used = quotas[key]
            yield {
                "record": "quota",
                "key": key,
                "namespace": ns,
                "min_chips": min_chips,
                "max_chips": max_chips,
                "used_chips": used,
            }

    # -------------------------------------------------------- self check

    def _canonical_state(self) -> Dict[str, Any]:
        return {
            "nodes": {n: st.canonical() for n, st in self._nodes.items()},
            "bound": dict(self._bound),
            "pending": dict(self._pending),
            "quotas": dict(self._quotas),
        }

    def self_check(self, store=None) -> List[str]:
        """Diff the incrementally-maintained instantaneous state against a
        from-scratch recomputation off the store. Empty list = clean.

        Skips (returns clean) when the store has moved past the ledger's
        watermark — the comparison would race concurrent writers; the
        auditor's sampling and the chaos oracle's quiesced polling both
        reach the quiet case."""
        store = store if store is not None else self.store
        with self._lock:
            if store.revision != self._revision:
                return []
            live = self._canonical_state()
        shadow = state_from_store(store)
        if store.revision != self._revision:
            return []  # a writer slipped in mid-recompute: racy, skip
        diffs: List[str] = []
        for section in ("nodes", "bound", "pending", "quotas"):
            a, b = live[section], shadow[section]
            for key in sorted(set(a) | set(b)):
                if a.get(key) != b.get(key):
                    diffs.append(
                        f"{section}[{key}]: incremental={a.get(key)!r} "
                        f"store={b.get(key)!r}"
                    )
        return diffs


def state_from_store(store) -> Dict[str, Any]:
    """The ledger's instantaneous state recomputed from scratch off the
    store — the shadow side of :meth:`CapacityLedger.self_check`."""
    nodes: Dict[str, tuple] = {}
    for node in store.list("Node", copy=False):
        total = int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
        if total > 0:
            nodes[node.metadata.name] = _NodeState(node, total).canonical()
    bound: Dict[str, Tuple[str, int, str]] = {}
    pending: Dict[str, Tuple[int, str]] = {}
    for pod in store.list("Pod", copy=False):
        chips = _pod_chips(pod)
        if chips <= 0:
            continue
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        phase = pod.status.phase
        if pod.spec.node_name and phase in ("Pending", "Running"):
            bound[key] = (pod.spec.node_name, chips, pod.metadata.namespace)
        elif phase == "Pending":
            pending[key] = (chips, pod.metadata.namespace)
    quotas: Dict[str, Tuple[str, int, int, int]] = {}
    for quota in store.list("ElasticQuota", copy=False):
        key = f"{quota.metadata.namespace}/{quota.metadata.name}"
        quotas[key] = (
            quota.metadata.namespace,
            _quota_chips(quota.spec.min),
            _quota_chips(quota.spec.max),
            _quota_chips(quota.status.used),
        )
    return {"nodes": nodes, "bound": bound, "pending": pending, "quotas": quotas}
