"""Pallas TPU kernels for the hot ops of the JAX workloads.

The reference suite has no compute kernels (it is a Kubernetes operator,
SURVEY.md §5); these belong to the TPU build's workload side — the models
the partitioner places onto carved slices. Kernels follow the
HBM→VMEM→MXU dataflow: blocks staged into VMEM by BlockSpecs, matmuls on
the MXU in float32 accumulation, elementwise work on the VPU.
"""
from nos_tpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
