"""Flash attention as Pallas TPU kernels — forward AND backward.

Blockwise exact attention (the same online-softmax math as
nos_tpu/parallel/ring_attention.py, but within one chip): the [S, S] score
matrix never exists — the grid streams key/value blocks through the MXU
while running max / normalizer / accumulator live in VMEM scratch. K/V
ride the grid's innermost dimension as (blk_k, hd) blocks, so Pallas
pipelines their HBM→VMEM DMAs against compute; VMEM per step is
O(blk_q·hd + blk_k·hd), independent of S — the long-context headroom the
dense path lacks.

Training-capable: ``flash_attention`` carries a ``jax.custom_vjp`` whose
backward recomputes probabilities blockwise from the saved logsumexp
(never materializing [S, S]) in two more Pallas kernels — one streaming
K/V per query block (dq), one streaming Q per key/value block (dk/dv).

Every kernel takes GLOBAL position offsets for q and kv (SMEM scalars, so
they may be traced — e.g. ``axis_index`` under shard_map). That is what
lets ring attention (nos_tpu/parallel/ring_attention.py) run these same
kernels per rotating K/V block with exact cross-chip causality:
``flash_attention_block`` returns the (out, logsumexp) partials that
merge across ring steps, and ``flash_block_grads`` the matching
per-block gradients.

Grid: (batch, q_heads, Sq/blk_q, Skv/blk_k). GQA is free — the K/V
BlockSpec index_map sends query head h to kv head h // group, so kv
blocks are fetched once per group without materializing the expanded
heads; the backward accumulates dk/dv per query head and group-sums
outside the kernel. Causal blocks entirely in the future are skipped with
``pl.when``.

Replaces the reference's dense-attention workloads (nos has no kernels —
its "workloads" are Pods); this is the TPU build's own perf frontier.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def validate_window(causal: bool, window) -> None:
    """Shared contract for every windowed-attention entry point (the
    single-chip kernel and both SP strategies): a window silently ignored
    under causal=False, or a 0-width band NaN-ing the softmax, must be a
    loud error everywhere."""
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")


def _block_needed(blk_q: int, blk_k: int, q_start, k_start, causal, window):
    """Whether a (q block, k block) pair can contribute any unmasked
    entry. ONE definition for all three kernels — forward and backward
    must agree on block coverage or gradients silently go wrong."""
    if not causal:
        return True
    needed = k_start <= q_start + blk_q - 1  # not fully in the future
    if window is not None:
        needed = needed & (k_start + blk_k - 1 >= q_start - window + 1)
    return needed


def _kv_block_span(qi, blk_q: int, blk_k: int, window):
    """Inclusive (lo, hi) kv-block index range q block ``qi`` can touch
    under causal (+ optional sliding-window) masking with ZERO offsets.
    Drives the compact grid: the inner kv step walks [lo, lo+steps) and
    clamps to hi, so steps past the band re-request the SAME block —
    Pallas elides the copy when consecutive grid steps map to identical
    block indices, which turns the skipped blocks' HBM traffic (the
    bulk of a bandwidth-bound attention) into nothing, not just their
    MXU work. r05 on-chip: windowed flash was SLOWER than full-causal
    at 4k/8k because pl.when skipped only compute while every K/V block
    still streamed."""
    hi = (qi * blk_q + blk_q - 1) // blk_k
    if window is None:
        lo = hi * 0
    else:
        lo = jnp.maximum(0, (qi * blk_q - window + 1) // blk_k)
    return lo, hi


def _q_block_span(kb, blk_q: int, blk_k: int, window, n_q: int):
    """Inclusive (lo, hi) q-block index range kv block ``kb`` feeds —
    the dkv-kernel mirror of _kv_block_span (zero offsets)."""
    lo = (kb * blk_k) // blk_q
    if window is None:
        hi = lo * 0 + (n_q - 1)
    else:
        hi = jnp.minimum(n_q - 1, (kb * blk_k + blk_k + window - 2) // blk_q)
    return lo, hi


def _compact_step(i, lo, hi):
    """Remapped block index + validity for compact inner step ``i``
    walking the inclusive [lo, hi] span. THE one definition of the
    remap — kernels and BlockSpec index maps must agree exactly, or a
    kernel computes a mask for a block the pipeline never fetched.
    Clamped steps repeat ``hi`` (Pallas elides the re-copy) and must be
    compute-skipped via the returned validity."""
    raw = lo + i
    return jnp.minimum(raw, hi), raw <= hi


# Kill-switch for the compact banded grid (NOS_FLASH_COMPACT=0): the
# remapped index maps are exercised in interpret mode by tests, but a
# Mosaic toolchain that rejects them should not take the whole flash
# path down — flipping this env (or calling set_compact(False) and
# jax.clear_caches()) restores the full rectangular grid (correct,
# just with the skipped blocks' DMA back).
_COMPACT_DEFAULT = os.environ.get("NOS_FLASH_COMPACT", "1") != "0"


def set_compact(enabled: bool) -> None:
    """Runtime flip of the compact-grid default (callers must
    jax.clear_caches() to drop already-traced programs)."""
    global _COMPACT_DEFAULT
    _COMPACT_DEFAULT = bool(enabled)


def _static_zero(off) -> bool:
    """True only for a compile-time zero offset — the precondition for
    the compact grid (its spans assume global positions start at 0). A
    traced offset (ring-attention block partials) can never qualify."""
    try:
        return int(off) == 0
    except TypeError:
        return False


def _compact_kv_steps(n_k: int, blk_q: int, blk_k: int, window) -> int:
    """Static inner-grid extent covering any q block's kv span."""
    if window is None:
        return n_k
    return min(n_k, (blk_q + window - 2) // blk_k + 2)


def _compact_q_steps(n_q: int, blk_q: int, blk_k: int, window) -> int:
    if window is None:
        return n_q
    return min(n_q, (blk_k + window - 2) // blk_q + 2)


def _causal_mask(blk_q: int, blk_k: int, q_start, k_start, window=None):
    """Causal (and optionally banded) mask: key <= query, and with
    ``window`` set, query - key < window — the Mistral sliding band."""
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    kv_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    mask = kv_pos <= q_pos
    if window is not None:
        mask = mask & (q_pos - kv_pos < window)
    return mask


def _smem_scalar_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _dimsem(n: int = 3):
    return pltpu.CompilerParams(
        dimension_semantics=("parallel",) * n + ("arbitrary",),
    )


# ------------------------------------------------------------------ forward


def _fwd_kernel(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, blk_q: int, blk_k: int, causal: bool, scale: float, window=None,
    compact: bool = False,
):
    ki = pl.program_id(3)
    n_k = pl.num_programs(3)
    q_start = pl.program_id(2) * blk_q + qoff_ref[0]
    if compact:
        # Same remap as the BlockSpec index_map: step ki visits block
        # min(lo+ki, hi); clamped steps are duplicates (no DMA) and
        # compute-skipped below.
        lo, hi_blk = _kv_block_span(pl.program_id(2), blk_q, blk_k, window)
        kb, in_span = _compact_step(ki, lo, hi_blk)
        k_start = kb * blk_k
    else:
        k_start = ki * blk_k + koff_ref[0]
        in_span = True

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal: blocks fully in the future contribute nothing — skip the MXU
    # work (compact grids also skip their DMA via the index remap above).
    # A sliding window also skips blocks fully PAST the band: for long
    # sequences the grid degenerates to O(S·W) compute instead of O(S²).
    needed = _block_needed(blk_q, blk_k, q_start, k_start, causal, window) & in_span

    @pl.when(needed)
    def _compute():
        # Matmuls stay in the input dtype (bf16) with f32 accumulation —
        # the MXU's native mode; casting inputs to f32 first would demote
        # every matmul to the slow f32 path. Softmax stats run f32 on the
        # VPU.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [blk_q, blk_k] f32
        if causal:
            s = jnp.where(
                _causal_mask(blk_q, blk_k, q_start, k_start, window), s, -jnp.inf
            )
        m_prev = m_scr[...]
        blk_max = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m)
        corr = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _finish():
        l = l_scr[...]
        # Rows with no valid key yet (a block entirely in this row's
        # future) hold l == 0: output 0 with lse = -inf so a later merge
        # (ring attention) weighs them at exp(-inf) = 0 instead of NaN.
        has_mass = l > 0.0
        safe_l = jnp.where(has_mass, l, 1.0)
        o_ref[0, 0] = jnp.where(
            has_mass, acc_scr[...] / safe_l, 0.0
        ).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            has_mass, m_scr[...] + jnp.log(safe_l), -jnp.inf
        )


def _fwd_pallas(qt, kt, vt, q_off, kv_off, *, causal, blk_q, blk_k, group, interpret, scale, window=None, compact=False):
    b, hq, sq, hd = qt.shape
    skv = kt.shape[2]
    n_k = skv // blk_k
    compact = (
        compact and causal and _static_zero(q_off) and _static_zero(kv_off)
    )
    steps = _compact_kv_steps(n_k, blk_q, blk_k, window) if compact else n_k
    grid = (b, hq, sq // blk_q, steps)
    kernel = functools.partial(
        _fwd_kernel, blk_q=blk_q, blk_k=blk_k, causal=causal, scale=scale,
        window=window, compact=compact,
    )
    if compact:
        def kv_map(bi, hi, qi, ki):
            lo, hi_blk = _kv_block_span(qi, blk_q, blk_k, window)
            return (bi, hi // group, _compact_step(ki, lo, hi_blk)[0], 0)
    else:
        def kv_map(bi, hi, qi, ki):
            return (bi, hi // group, ki, 0)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _smem_scalar_spec(),
            _smem_scalar_spec(),
            pl.BlockSpec((1, 1, blk_q, hd), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, blk_k, hd), kv_map),
            pl.BlockSpec((1, 1, blk_k, hd), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, blk_q, hd), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            # Row stats ride as [B, H, S, 1]: a trailing unit dim keeps the
            # block's minor dims legal for the TPU tiling (blk_q × 1).
            pl.BlockSpec((1, 1, blk_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, hd), qt.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, hd), jnp.float32),
        ],
        compiler_params=_dimsem(),
        interpret=interpret,
    )(jnp.asarray([q_off], jnp.int32), jnp.asarray([kv_off], jnp.int32), qt, kt, vt)


# ----------------------------------------------------------------- backward


def _bwd_p_ds(q, k, v, do, lse, delta, *, blk_q, blk_k, causal, scale, q_start, k_start, window=None):
    """Shared backward block math: recompute p from lse, form ds.

    lse/delta arrive as [blk_q, 1] f32 column stats and broadcast. Inputs
    stay bf16 into the MXU (f32 accumulate); p/ds round back to the input
    dtype for their second matmuls — same rounding as the forward. Rows
    with lse = -inf (no mass: fully-future rows of a ring block) produce
    p = exp(-inf - -inf) garbage unless guarded — mask them to zero."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    finite = jnp.isfinite(lse)
    p = jnp.where(finite, jnp.exp(s - jnp.where(finite, lse, 0.0)), 0.0)
    if causal:
        p = jnp.where(_causal_mask(blk_q, blk_k, q_start, k_start, window), p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - delta) * scale
    return p.astype(q.dtype), ds.astype(q.dtype)


def _dq_kernel(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
    *, blk_q: int, blk_k: int, causal: bool, scale: float, window=None,
    compact: bool = False,
):
    ki = pl.program_id(3)
    n_k = pl.num_programs(3)
    q_start = pl.program_id(2) * blk_q + qoff_ref[0]
    if compact:
        lo, hi_blk = _kv_block_span(pl.program_id(2), blk_q, blk_k, window)
        kb, in_span = _compact_step(ki, lo, hi_blk)
        k_start = kb * blk_k
    else:
        k_start = ki * blk_k + koff_ref[0]
        in_span = True

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    needed = _block_needed(blk_q, blk_k, q_start, k_start, causal, window) & in_span

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        _, ds = _bwd_p_ds(
            q, k, v, do, lse_ref[0, 0], delta_ref[0, 0],
            blk_q=blk_q, blk_k=blk_k, causal=causal, scale=scale,
            q_start=q_start, k_start=k_start, window=window,
        )
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == n_k - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(
    qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_scr, dv_scr,
    *, blk_q: int, blk_k: int, causal: bool, scale: float, window=None,
    compact: bool = False, n_q_total: int = 0,
):
    qi = pl.program_id(3)
    n_q = pl.num_programs(3)
    k_start = pl.program_id(2) * blk_k + koff_ref[0]
    if compact:
        lo, hi_blk = _q_block_span(
            pl.program_id(2), blk_q, blk_k, window, n_q_total
        )
        qb, in_span = _compact_step(qi, lo, hi_blk)
        q_start = qb * blk_q
    else:
        q_start = qi * blk_q + qoff_ref[0]
        in_span = True

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    needed = _block_needed(blk_q, blk_k, q_start, k_start, causal, window) & in_span

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        p, ds = _bwd_p_ds(
            q, k, v, do, lse_ref[0, 0], delta_ref[0, 0],
            blk_q=blk_q, blk_k=blk_k, causal=causal, scale=scale,
            q_start=q_start, k_start=k_start, window=window,
        )
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_pallas(qt, kt, vt, dot, lse, delta, q_off, kv_off, *, causal, blk_q, blk_k, group, interpret, scale, grad_dtype=None, window=None, compact=False):
    b, hq, sq, hd = qt.shape
    skv = kt.shape[2]
    compact = (
        compact and causal and _static_zero(q_off) and _static_zero(kv_off)
    )
    dq_dtype = grad_dtype or qt.dtype
    dkv_dtype = grad_dtype or kt.dtype
    kwargs = dict(blk_q=blk_q, blk_k=blk_k, causal=causal, scale=scale, window=window)
    offs = (jnp.asarray([q_off], jnp.int32), jnp.asarray([kv_off], jnp.int32))
    q_spec = pl.BlockSpec((1, 1, blk_q, hd), lambda bi, hi, qi, ki: (bi, hi, qi, 0))
    if compact:
        def _kv_idx(qi, ki):
            lo, hi_blk = _kv_block_span(qi, blk_q, blk_k, window)
            return _compact_step(ki, lo, hi_blk)[0]

        kv_spec = pl.BlockSpec(
            (1, 1, blk_k, hd),
            lambda bi, hi, qi, ki: (bi, hi // group, _kv_idx(qi, ki), 0),
        )
        kv_steps = _compact_kv_steps(skv // blk_k, blk_q, blk_k, window)
    else:
        kv_spec = pl.BlockSpec(
            (1, 1, blk_k, hd), lambda bi, hi, qi, ki: (bi, hi // group, ki, 0)
        )
        kv_steps = skv // blk_k
    row_spec = pl.BlockSpec((1, 1, blk_q, 1), lambda bi, hi, qi, ki: (bi, hi, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, compact=compact, **kwargs),
        grid=(b, hq, sq // blk_q, kv_steps),
        in_specs=[
            _smem_scalar_spec(), _smem_scalar_spec(),
            q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec,
        ],
        out_specs=pl.BlockSpec(
            (1, 1, blk_q, hd), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, hd), dq_dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, hd), jnp.float32)],
        compiler_params=_dimsem(),
        interpret=interpret,
    )(*offs, qt, kt, vt, dot, lse, delta)

    # dk/dv: stream Q blocks (innermost) per K/V block. Accumulated per
    # QUERY head ([B, Hq, Skv, hd]); the GQA group-sum happens outside.
    n_q = sq // blk_q
    if compact:
        def _q_idx(ki, qi):
            lo, hi_blk = _q_block_span(ki, blk_q, blk_k, window, n_q)
            return _compact_step(qi, lo, hi_blk)[0]

        q_spec_t = pl.BlockSpec(
            (1, 1, blk_q, hd), lambda bi, hi, ki, qi: (bi, hi, _q_idx(ki, qi), 0)
        )
        row_spec_t = pl.BlockSpec(
            (1, 1, blk_q, 1), lambda bi, hi, ki, qi: (bi, hi, _q_idx(ki, qi), 0)
        )
        q_steps = _compact_q_steps(n_q, blk_q, blk_k, window)
    else:
        q_spec_t = pl.BlockSpec(
            (1, 1, blk_q, hd), lambda bi, hi, ki, qi: (bi, hi, qi, 0)
        )
        row_spec_t = pl.BlockSpec(
            (1, 1, blk_q, 1), lambda bi, hi, ki, qi: (bi, hi, qi, 0)
        )
        q_steps = n_q
    kv_spec_t = pl.BlockSpec(
        (1, 1, blk_k, hd), lambda bi, hi, ki, qi: (bi, hi // group, ki, 0)
    )
    dkv_out = pl.BlockSpec((1, 1, blk_k, hd), lambda bi, hi, ki, qi: (bi, hi, ki, 0))
    dkh, dvh = pl.pallas_call(
        functools.partial(_dkv_kernel, compact=compact, n_q_total=n_q, **kwargs),
        grid=(b, hq, skv // blk_k, q_steps),
        in_specs=[
            _smem_scalar_spec(), _smem_scalar_spec(),
            q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t, row_spec_t,
        ],
        out_specs=[dkv_out, dkv_out],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, skv, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, skv, hd), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, hd), jnp.float32),
            pltpu.VMEM((blk_k, hd), jnp.float32),
        ],
        compiler_params=_dimsem(),
        interpret=interpret,
    )(*offs, qt, kt, vt, dot, lse, delta)
    hkv = hq // group
    dk = dkh.reshape(b, hkv, group, skv, hd).sum(axis=2).astype(dkv_dtype)
    dv = dvh.reshape(b, hkv, group, skv, hd).sum(axis=2).astype(dkv_dtype)
    return dq, dk, dv


# --------------------------------------------------------------- custom_vjp


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, blk_q, blk_k, interpret, window):
    out, _ = _flash_fwd(q, k, v, causal, blk_q, blk_k, interpret, window)
    return out


def _flash_fwd(q, k, v, causal, blk_q, blk_k, interpret, window):
    b, s, hq, hd = q.shape
    group = hq // k.shape[2]
    scale = 1.0 / math.sqrt(hd)
    # [B, H, S, hd] puts (sequence, head_dim) in the tiled trailing dims.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    ot, lse = _fwd_pallas(
        qt, kt, vt, 0, 0, causal=causal, blk_q=blk_q, blk_k=blk_k,
        group=group, interpret=interpret, scale=scale, window=window,
        compact=_COMPACT_DEFAULT,
    )
    out = ot.transpose(0, 2, 1, 3)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, blk_q, blk_k, interpret, window, res, do):
    q, k, v, out, lse = res
    delta = _delta(do, out)
    dq, dk, dv = _bwd_pallas(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        do.transpose(0, 2, 1, 3),
        lse,
        delta,
        0, 0,
        causal=causal, blk_q=blk_q, blk_k=blk_k,
        group=q.shape[2] // k.shape[2], interpret=interpret,
        scale=1.0 / math.sqrt(q.shape[3]), window=window,
        compact=_COMPACT_DEFAULT,
    )
    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3),
        dv.transpose(0, 2, 1, 3),
    )


def _delta(do, out):
    """delta_i = rowsum(do_i · o_i): cheap elementwise, XLA fuses it.
    [B, S, H, hd] inputs → [B, H, S, 1]."""
    return jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    ).transpose(0, 2, 1)[..., None]


_flash.defvjp(_flash_fwd, _flash_bwd)


def _divisor_block(s: int, blk: int) -> int:
    """Largest divisor of s that is <= blk."""
    blk = min(blk, s)
    while s % blk:
        blk -= 1
    return blk


def default_blocks(window: "int | None") -> "tuple[int, int]":
    """Measured-best default (blk_q, blk_k) on v5e (BENCH_r05_tpu.json
    attn sweep @ 8x2048: 512x1024 is 3.03x dense vs 1.48x for 128x256).
    Windowed configs use 512x512: under the compact grid each q block
    streams ceil((blk_q + W - 1)/blk_k)+1 kv blocks, so for a ~1k
    window 512x512 moves the fewest K/V bytes per q block while keeping
    full-width MXU q tiles."""
    return (512, 512) if window is not None else (512, 1024)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    blk_q: "int | None" = None,
    blk_k: "int | None" = None,
    interpret: bool = False,
    window: "int | None" = None,
) -> jax.Array:
    """q [B, S, Hq, hd], k/v [B, S, Hkv, hd] → [B, S, Hq, hd].

    Hq must be a multiple of Hkv (GQA). S must divide by the block sizes
    (block sizes clamp down to S for short sequences). Differentiable:
    the custom_vjp backward recomputes attention blockwise from the saved
    logsumexp — O(S) memory end to end.

    ``window`` (requires causal): Mistral-style sliding band — query i
    attends keys (i-window, i]. Blocks fully past the band are SKIPPED,
    so long-sequence compute degenerates to O(S·window) instead of O(S²)
    — banding is where the blockwise grid beats dense masking outright.

    Block sizes default by shape (see ``default_blocks``); pass
    ``blk_q``/``blk_k`` to override.
    """
    b, s, hq, hd = q.shape
    hkv = k.shape[2]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    validate_window(causal, window)
    auto_q, auto_k = default_blocks(window)
    # Clamp block sizes to the largest divisor of S: arbitrary prompt
    # lengths work, power-of-two lengths keep full MXU-shaped blocks.
    blk_q = _divisor_block(s, auto_q if blk_q is None else blk_q)
    blk_k = _divisor_block(s, auto_k if blk_k is None else blk_k)
    return _flash(q, k, v, causal, blk_q, blk_k, interpret, window)


# ---------------------------------------------------------- block partials


def flash_attention_block(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_offset,
    kv_offset,
    *,
    causal: bool = True,
    blk_q: int = 256,
    blk_k: int = 512,
    interpret: bool = False,
    window: "int | None" = None,
):
    """Forward PARTIALS of q [B, Sq, Hq, hd] against one K/V block
    [B, Skv, Hkv, hd] whose global positions start at the (possibly
    traced) offsets → (out [B, Sq, Hq, hd], lse [B, Hq, Sq, 1]).

    Rows with no causally-visible key in this block return out = 0 with
    lse = -inf, so partials from different blocks merge exactly with
    ``merge_flash_partials`` — the kernel-side engine of ring attention.
    """
    b, sq, hq, hd = q.shape
    if hq % k.shape[2]:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {k.shape[2]}")
    blk_q = _divisor_block(sq, blk_q)
    blk_k = _divisor_block(k.shape[1], blk_k)
    group = hq // k.shape[2]
    ot, lse = _fwd_pallas(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        q_offset, kv_offset,
        causal=causal, blk_q=blk_q, blk_k=blk_k,
        group=group, interpret=interpret, scale=1.0 / math.sqrt(hd),
        window=window,
    )
    return ot.transpose(0, 2, 1, 3), lse


def merge_flash_partials(out_a, lse_a, out_b, lse_b):
    """Exact online-softmax merge of two block partials (out in
    [B, S, H, hd], lse in [B, H, S, 1]) → (out, lse) as if both blocks had
    been attended together."""
    lse_new = jnp.logaddexp(lse_a, lse_b)  # -inf + -inf handled exactly
    w_a = jnp.exp(jnp.where(jnp.isfinite(lse_a), lse_a - lse_new, -jnp.inf))
    w_b = jnp.exp(jnp.where(jnp.isfinite(lse_b), lse_b - lse_new, -jnp.inf))
    # [B, H, S, 1] weights → [B, S, H, 1] to match the out layout
    w_a = w_a.transpose(0, 2, 1, 3)
    w_b = w_b.transpose(0, 2, 1, 3)
    out = out_a.astype(jnp.float32) * w_a + out_b.astype(jnp.float32) * w_b
    return out.astype(out_a.dtype), lse_new


def flash_block_grads(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    do: jax.Array,
    q_offset,
    kv_offset,
    *,
    causal: bool = True,
    blk_q: int = 256,
    blk_k: int = 512,
    interpret: bool = False,
    grad_dtype=None,
    delta: jax.Array = None,
    window: "int | None" = None,
):
    """Per-block gradients matching ``flash_attention_block``: the
    contribution of THIS K/V block to (dq, dk, dv), given the MERGED
    (out, lse) of the full attention (the standard flash backward math —
    each block's dq/dk/dv term only needs the global row stats).

    ``grad_dtype`` (e.g. f32 for the ring path, whose contributions are
    summed across hops AFTER this call) overrides the input dtypes;
    ``delta`` lets a caller that invokes this per ring hop precompute the
    loop-invariant rowsum(do·out) once."""
    b, sq, hq, hd = q.shape
    if hq % k.shape[2]:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {k.shape[2]}")
    blk_q = _divisor_block(sq, blk_q)
    blk_k = _divisor_block(k.shape[1], blk_k)
    if delta is None:
        delta = _delta(do, out)
    dq, dk, dv = _bwd_pallas(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        do.transpose(0, 2, 1, 3),
        lse,
        delta,
        q_offset, kv_offset,
        causal=causal, blk_q=blk_q, blk_k=blk_k,
        group=hq // k.shape[2], interpret=interpret,
        scale=1.0 / math.sqrt(hd),
        grad_dtype=grad_dtype, window=window,
    )
    return (
        dq.transpose(0, 2, 1, 3),
        dk.transpose(0, 2, 1, 3),
        dv.transpose(0, 2, 1, 3),
    )
