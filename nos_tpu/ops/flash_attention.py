"""Flash attention as a Pallas TPU kernel.

Blockwise exact attention (the same online-softmax math as
nos_tpu/parallel/ring_attention.py, but within one chip): the [S, S] score
matrix never leaves VMEM — each grid step holds one query block and streams
key/value blocks through the MXU, keeping running max / normalizer /
accumulator in float32. Memory per step is O(blk_q·S + S·hd) VMEM instead
of O(S²) HBM, and the matmuls are MXU-shaped (last dim 128-padded by the
caller's head_dim choice).

Grid: (batch, q_heads, S/blk_q). GQA is free — the K/V BlockSpec index_map
sends query head h to kv head h // group, so kv blocks are fetched once per
group without materializing the expanded heads.

Forward-only: wrap in jax.custom_vjp with a recompute backward before using
under grad (the dense path remains the training default; this kernel serves
inference and serving benches).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_k: int, causal: bool, scale: float):
    q = q_ref[0, 0].astype(jnp.float32)  # [blk_q, hd]
    blk_q = q.shape[0]
    seq_len = k_ref.shape[2]
    n_kv_blocks = seq_len // blk_k
    q_start = pl.program_id(2) * blk_q

    m0 = jnp.full((blk_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    acc0 = jnp.zeros((blk_q, q.shape[1]), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * blk_k, blk_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [blk_q, blk_k]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            kv_pos = j * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(kv_pos <= q_pos, s, -jnp.inf)
        blk_max = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        p = jnp.exp(s - safe_m)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return new_m, l, acc

    if causal:
        # Blocks fully in the future contribute nothing: stop the stream at
        # the last block intersecting this query block's causal frontier.
        upper = jax.lax.div(q_start + blk_q + blk_k - 1, blk_k)
        upper = jnp.minimum(upper, n_kv_blocks)
    else:
        upper = n_kv_blocks
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


def _divisor_block(s: int, blk: int) -> int:
    """Largest divisor of s that is <= blk."""
    blk = min(blk, s)
    while s % blk:
        blk -= 1
    return blk


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    blk_q: int = 128,
    blk_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """q [B, S, Hq, hd], k/v [B, S, Hkv, hd] → [B, S, Hq, hd].

    Hq must be a multiple of Hkv (GQA). S must divide by the block sizes
    (block sizes clamp down to S for short sequences).
    """
    b, s, hq, hd = q.shape
    hkv = k.shape[2]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    group = hq // hkv
    # Clamp block sizes to the largest divisor of S: arbitrary prompt
    # lengths work, power-of-two lengths keep full MXU-shaped blocks.
    blk_q = _divisor_block(s, blk_q)
    blk_k = _divisor_block(s, blk_k)

    # [B, H, S, hd] puts (sequence, head_dim) in the tiled trailing dims.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    kernel = functools.partial(
        _flash_kernel, blk_k=blk_k, causal=causal, scale=1.0 / math.sqrt(hd)
    )
    out = pl.pallas_call(
        kernel,
        grid=(b, hq, s // blk_q),
        in_specs=[
            pl.BlockSpec(
                (1, 1, blk_q, hd),
                lambda bi, hi, qi: (bi, hi, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, s, hd),
                lambda bi, hi, qi: (bi, hi // group, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, s, hd),
                lambda bi, hi, qi: (bi, hi // group, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, blk_q, hd),
            lambda bi, hi, qi: (bi, hi, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b, hq, s, hd), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)
