"""CLI dispatcher: `python -m nos_tpu <component> --config <file>`.

Mirrors the reference's six binaries (SURVEY.md §2.1). `run` starts the
whole suite in one process (kind-style); `export-metrics` is the one-shot
telemetry job.
"""
import sys


def main() -> int:
    commands = {
        "run": "the full suite (operator+partitioner+scheduler+agents)",
        "operator": "EQ/CEQ reconcilers + validating webhooks",
        "partitioner": "dynamic TPU slice partitioner control plane",
        "scheduler": "capacity/gang/topology-aware scheduler",
        "tpuagent": "per-node slice reporter+actuator daemon (NODE_NAME)",
        "sharingagent": "per-node sharing reporter daemon (NODE_NAME)",
        "export-metrics": "one-shot installation telemetry snapshot",
        "replay": "deterministic offline replay of a flight-recorder log",
        "chaos": "seeded fault injection with convergence oracles",
        "bench": "the utilization benchmark",
    }
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print("usage: python -m nos_tpu <command> [args]\n\ncommands:")
        for name, desc in commands.items():
            print(f"  {name:16s} {desc}")
        return 0 if len(sys.argv) >= 2 else 2
    command, argv = sys.argv[1], sys.argv[2:]
    if command == "run":
        from nos_tpu.cmd.run import main as run_main

        return run_main(argv)
    if command in ("operator", "partitioner", "scheduler", "tpuagent", "sharingagent"):
        import importlib

        module = importlib.import_module(f"nos_tpu.cmd.{command}")
        return module.main(argv)
    if command == "export-metrics":
        from nos_tpu.cmd.metricsexporter import main as export_main

        return export_main(argv)
    if command == "replay":
        from nos_tpu.cmd.replay import main as replay_main

        return replay_main(argv)
    if command == "chaos":
        from nos_tpu.cmd.chaos import main as chaos_main

        return chaos_main(argv)
    if command == "bench":
        import os

        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        try:
            import bench
        except ModuleNotFoundError:
            print(
                "bench.py not found (it lives at the repo root, not in the "
                "installed package); run from a source checkout",
                file=sys.stderr,
            )
            return 1
        bench.main()
        return 0
    print(f"unknown command {command!r}; see --help", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
