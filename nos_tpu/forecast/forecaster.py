"""PlacementForecaster: the wired forecast subsystem.

Runs OFF the plan path: the partitioner's cycle hook
(:meth:`notify_cycle`) only stashes the cycle's pending batch and wakes
a dedicated background thread (registered with the sampling profiler,
so /debug/profile attributes its ``forecast.*`` phases). The thread owns
its OWN planner and its OWN :class:`IncrementalSnapshotMaintainer` —
version-keyed memos stay warm across forecast cycles without ever
touching the live control loop's planner state, and steady-state replan
latency stays within the <=2% overhead budget the perf guard enforces.

Per run it publishes:

- per-gang earliest-feasible-start ETAs (``nos_tpu_gang_eta_seconds``),
- backfill-safety verdicts (``nos_tpu_backfill_unsafe_total``),
- the defrag advisor's recommendations,
- a ``forecast.cycle`` flight record stamping every forecast,

and joins each published ETA against the actually-observed bind time
(via the capacity ledger's gang-bound listener) into the calibration
tracker — ``nos_tpu_forecast_accuracy_ratio`` and the
``forecast.outcome`` records the replay harness recomputes bit-exactly.

Deterministic paths (:meth:`run_once` with caller-supplied ``now`` and
``pending``) never read a wall clock; the thread loop is the only place
``time.time()`` appears.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from nos_tpu.forecast.accuracy import CalibrationTracker
from nos_tpu.forecast.advisor import DefragAdvisor
from nos_tpu.forecast.engine import STAGE_FEASIBLE_NOW, ForecastEngine
from nos_tpu.util import metrics
from nos_tpu.util.profiling import PROFILER
from nos_tpu.util.tracing import TRACER

log = logging.getLogger("nos_tpu.forecast")


class PlacementForecaster:
    def __init__(
        self,
        store,
        cluster_state,
        planner,
        snapshot_taker,
        kind: str = "tpu",
        capacity_ledger=None,
        flight_recorder=None,
        min_interval_seconds: float = 0.25,
        default_cycle_seconds: float = 1.0,
        default_reconfig_seconds: float = 0.5,
        max_gangs: int = 32,
        max_backfill_pairs: int = 64,
        small_pod_chips: int = 2,
        advisor_free_fraction: float = 0.5,
        advisor_max_proposals: int = 4,
    ) -> None:
        self.store = store
        self.cluster_state = cluster_state
        self.kind = kind
        self.ledger = capacity_ledger
        self.flight = flight_recorder
        self.min_interval_seconds = min_interval_seconds
        self.default_reconfig_seconds = default_reconfig_seconds
        self.engine = ForecastEngine(
            planner,
            max_gangs=max_gangs,
            max_backfill_pairs=max_backfill_pairs,
            small_pod_chips=small_pod_chips,
        )
        self.advisor = DefragAdvisor(
            self.engine,
            free_fraction=advisor_free_fraction,
            max_proposals=advisor_max_proposals,
        )
        self.snapshot_taker = snapshot_taker
        self._maintainer = None  # built lazily: its watch starts on first use
        self.calibration = CalibrationTracker()
        # One forecast computation at a time: the background thread and an
        # on-demand /debug/forecast?refresh=1 must not interleave trials
        # on the shared base snapshot.
        self._run_lock = threading.Lock()
        # Guards the cheap shared state below (stamps, clocks, last result).
        self._state_lock = threading.Lock()
        self._outstanding: Dict[str, Dict[str, Any]] = {}
        self._feasible_since: Dict[str, float] = {}
        self._last_payload: Optional[Dict[str, Any]] = None
        self._pending_batch: List[Any] = []
        self._batch_now: Optional[float] = None
        self._batch_trace_id = ""
        self._journey = None
        # Measured cycle cadence (EWMA over notify timestamps) — the
        # "feasible now binds next cycle" ETA unit.
        self._cycle_seconds = default_cycle_seconds
        self._last_notify: Optional[float] = None
        self.runs = 0
        self.backfill_unsafe_total = 0
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_run_monotonic = 0.0
        if capacity_ledger is not None and hasattr(
            capacity_ledger, "add_gang_bound_listener"
        ):
            capacity_ledger.add_gang_bound_listener(self._on_gang_bound)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        # Event-driven (woken by plan-cycle notifies), so periodic=False:
        # a quiet cluster legitimately never forecasts.
        from nos_tpu.timeline.watchdog import WATCHDOG

        WATCHDOG.register(
            f"forecast-{self.kind}",
            periodic=False,
            thread_name=f"forecast-{self.kind}",
            counter_fn=lambda: self.runs,
        )
        self._thread = threading.Thread(
            target=self._loop, name=f"forecast-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        from nos_tpu.timeline.watchdog import WATCHDOG

        WATCHDOG.unregister(f"forecast-{self.kind}")

    def _loop(self) -> None:
        from nos_tpu.timeline.watchdog import WATCHDOG

        PROFILER.register_thread(name=f"forecast-{self.kind}")
        try:
            while True:
                self._wake.wait()
                self._wake.clear()
                WATCHDOG.beat(f"forecast-{self.kind}")
                if self._stop.is_set():
                    return
                # Throttle: a notify storm (every plan cycle under a
                # burst) must not turn into a forecast storm.
                elapsed = time.monotonic() - self._last_run_monotonic
                if elapsed < self.min_interval_seconds:
                    if self._stop.wait(self.min_interval_seconds - elapsed):
                        return
                self._last_run_monotonic = time.monotonic()
                try:
                    self.run_once()
                except Exception:  # pragma: no cover - diagnostics only
                    log.exception("forecast cycle failed")
        finally:
            PROFILER.unregister_thread()

    # ------------------------------------------------------------- triggers

    def notify_cycle(
        self,
        pending,
        now: Optional[float] = None,
        trace_id: str = "",
        journey=None,
    ) -> None:
        """Partitioner cycle hook: stash the batch, wake the thread.
        Called on the control loop — must stay O(pending)."""
        now = time.time() if now is None else now
        with self._state_lock:
            if self._last_notify is not None:
                interval = max(0.0, now - self._last_notify)
                if 0.0 < interval < 60.0:
                    self._cycle_seconds = (
                        0.7 * self._cycle_seconds + 0.3 * interval
                    )
            self._last_notify = now
            self._pending_batch = list(pending)
            self._batch_now = now
            self._batch_trace_id = trace_id
            self._journey = journey
        self._wake.set()

    # ------------------------------------------------------------- forecast

    def run_once(
        self,
        now: Optional[float] = None,
        pending=None,
        cycle_seconds: Optional[float] = None,
        reconfig_seconds: Optional[float] = None,
    ) -> Optional[Dict[str, Any]]:
        """One full forecast pass; returns the published payload. All
        inputs are overridable so tests and the bench drive it with a
        virtual clock and a fixed pending set."""
        with self._run_lock:
            with self._state_lock:
                if pending is None:
                    pending = list(self._pending_batch)
                if now is None:
                    now = (
                        self._batch_now
                        if self._batch_now is not None
                        else time.time()
                    )
                trace_id = self._batch_trace_id
                journey = self._journey
                if cycle_seconds is None:
                    cycle_seconds = self._cycle_seconds
            if reconfig_seconds is None:
                reconfig_seconds = self._measured_reconfig_seconds()
            clocks = (
                self.ledger.gang_clocks() if self.ledger is not None else {}
            )
            parent = (
                journey
                if journey is not None and not getattr(journey, "ended", True)
                else None
            )
            with TRACER.span(
                "forecast.cycle",
                parent=parent,
                pending=len(pending),
                trace_id=trace_id,
            ) as span:
                snapshot, dirty = self._snapshot()
                result = self.engine.forecast(
                    snapshot,
                    pending,
                    now,
                    clocks=clocks,
                    cycle_seconds=cycle_seconds,
                    reconfig_seconds=reconfig_seconds,
                )
                result.advisor = self.advisor.advise(
                    snapshot,
                    pending,
                    result.gangs,
                    now,
                    clocks=clocks,
                    cycle_seconds=cycle_seconds,
                    reconfig_seconds=reconfig_seconds,
                )
                span.set_attributes(
                    gangs=len(result.gangs),
                    backfill_unsafe=result.unsafe_count,
                    dirty_nodes=len(dirty),
                )
            self._publish(result, now, trace_id)
            payload = result.payload()
            with self._state_lock:
                self._last_payload = payload
            return payload

    def _snapshot(self):
        if self._maintainer is None:
            from nos_tpu.controllers.partitioner.incremental import (
                IncrementalSnapshotMaintainer,
            )

            self._maintainer = IncrementalSnapshotMaintainer(
                self.store, self.snapshot_taker, kind=f"{self.kind}-forecast"
            )
        return self._maintainer.snapshot(self.cluster_state)

    def _measured_reconfig_seconds(self) -> float:
        if self.ledger is not None and hasattr(
            self.ledger, "mean_reconfig_seconds"
        ):
            return self.ledger.mean_reconfig_seconds(
                default=self.default_reconfig_seconds
            )
        return self.default_reconfig_seconds

    def _publish(self, result, now: float, trace_id: str) -> None:
        self.runs += 1
        metrics.FORECAST_RUNS.inc()
        unsafe = result.unsafe_count
        if unsafe:
            self.backfill_unsafe_total += unsafe
            metrics.BACKFILL_UNSAFE_TOTAL.inc(unsafe)
        stamps: Dict[str, Dict[str, Any]] = {}
        for gang in result.gangs:
            if gang.eta_seconds is not None:
                metrics.GANG_ETA_SECONDS.labels(stage=gang.stage).observe(
                    gang.eta_seconds
                )
            stamps[gang.gang] = {
                "now": now,
                "eta_seconds": gang.eta_seconds,
                "stage": gang.stage,
            }
        with self._state_lock:
            # Replace wholesale: forecasts only cover currently-pending
            # gangs, so anything older is bound (listener popped it) or
            # gone (deleted/timed out — nothing to score).
            self._outstanding = stamps
            for gang in result.gangs:
                if gang.stage == STAGE_FEASIBLE_NOW:
                    self._feasible_since.setdefault(gang.gang, now)
                else:
                    self._feasible_since.pop(gang.gang, None)
            live = {g.gang for g in result.gangs}
            for key in [k for k in self._feasible_since if k not in live]:
                del self._feasible_since[key]
        if self.flight is not None:
            self.flight.record_forecast(
                revision=self.store.revision if self.store is not None else 0,
                now=now,
                trace_id=trace_id,
                gangs=[g.payload() for g in result.gangs],
                backfill_unsafe=unsafe,
                advisor_validated=bool(
                    (result.advisor or {}).get("validated")
                ),
            )

    # ---------------------------------------------------- accuracy joining

    def _on_gang_bound(
        self, gang: str, now: float, wait_seconds: float
    ) -> None:
        """Capacity-ledger listener: join the bind against the last
        published forecast for this gang."""
        with self._state_lock:
            stamp = self._outstanding.pop(gang, None)
            self._feasible_since.pop(gang, None)
            if stamp is None:
                return
            actual = max(0.0, now - stamp["now"])
            sample = self.calibration.add(
                stamp["eta_seconds"],
                actual,
                wait_seconds,
                stage=stamp["stage"],
            )
            payload = self.calibration.payload()
        if self.flight is not None:
            self.flight.record_forecast_outcome(
                gang=gang,
                now=now,
                stage=stamp["stage"],
                eta_seconds=stamp["eta_seconds"],
                actual_seconds=actual,
                wait_seconds=wait_seconds,
                calibration=payload,
            )
        if sample is not None:
            metrics.FORECAST_ACCURACY_RATIO.labels(quantile="p50").set(
                payload["p50_ratio"]
            )
            metrics.FORECAST_ACCURACY_RATIO.labels(quantile="p95").set(
                payload["p95_ratio"]
            )

    # --------------------------------------------------------------- checks

    def stale_feasible_now(
        self, now: float, limit_seconds: Optional[float] = None
    ) -> List[str]:
        """Gangs continuously forecast feasible-now for longer than
        ``limit_seconds`` without binding — the forecast-calibrated chaos
        oracle's violation set. Default limit: 3 measured cycles."""
        with self._state_lock:
            if limit_seconds is None:
                limit_seconds = 3.0 * self._cycle_seconds
            return sorted(
                gang
                for gang, since in self._feasible_since.items()
                if now - since > limit_seconds
            )

    # ---------------------------------------------------------------- debug

    def debug_payload(self, refresh: bool = False) -> Dict[str, Any]:
        if refresh:
            try:
                self.run_once(now=time.time())
            except Exception:  # pragma: no cover - diagnostics only
                log.exception("on-demand forecast failed")
        with self._state_lock:
            last = self._last_payload
            payload: Dict[str, Any] = {
                "kind": self.kind,
                "runs": self.runs,
                "cycle_seconds": self._cycle_seconds,
                "reconfig_seconds": self._measured_reconfig_seconds(),
                "outstanding": len(self._outstanding),
                "backfill_unsafe_total": self.backfill_unsafe_total,
                "calibration": self.calibration.payload(),
                "forecast": last,
            }
        return payload
