"""Read-only defrag advisor: re-carve recommendations, never actuation.

BENCH_r05's gap (ROADMAP item 2) is near-empty boards carved for a
profile mix the pending queue no longer wants: 8-chip gangs wait while
1-2 chip slivers sit free. The advisor proposes the re-carve set that
moves those boards toward the queue's demanded mix and prices each
recommendation honestly: the proposal is applied on a forked snapshot,
every pending gang is re-forecast against the hypothetical geometry,
and the predicted saving is the ETA improvement weighted by each gang's
pending chips (chip-seconds of queue wait the re-carve would remove).
A recommendation only reports ``validated: true`` when that shadow sim
confirms some gang actually starts earlier and none gets worse.

Recommendations surface on /debug/forecast and in BENCH_forecast.json;
nothing here writes to the store — actuation is a later PR's decision,
gated on the accuracy calibration this PR measures.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from nos_tpu.forecast.engine import (
    _STAGE_RANK,
    ForecastEngine,
    GangForecast,
    _free_chips,
    _pod_chips,
)
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot
from nos_tpu.partitioning.core.tracker import SliceTracker
from nos_tpu.util.tracing import TRACER


class DefragAdvisor:
    """Proposes re-carves of near-empty boards toward the pending queue's
    profile mix. ``free_fraction`` is the near-empty threshold (free
    chips / total chips at or above it qualifies a node)."""

    def __init__(
        self,
        engine: ForecastEngine,
        free_fraction: float = 0.5,
        max_proposals: int = 4,
    ) -> None:
        self.engine = engine
        self.free_fraction = free_fraction
        self.max_proposals = max_proposals

    def advise(
        self,
        snapshot: ClusterSnapshot,
        pending,
        before: List[GangForecast],
        now: float,
        clocks: Optional[Dict[str, Dict[str, float]]] = None,
        cycle_seconds: float = 1.0,
        reconfig_seconds: float = 0.5,
    ) -> Dict[str, Any]:
        """Advisor payload for one forecast cycle. ``before`` is the
        cycle's baseline gang classification (so the shadow sim compares
        against exactly what was published, not a recomputation)."""
        with TRACER.span("forecast.advisor"):
            return self._advise(
                snapshot,
                pending,
                before,
                now,
                clocks or {},
                cycle_seconds,
                reconfig_seconds,
            )

    def _advise(
        self,
        snapshot: ClusterSnapshot,
        pending,
        before: List[GangForecast],
        now: float,
        clocks: Dict[str, Dict[str, float]],
        cycle_seconds: float,
        reconfig_seconds: float,
    ) -> Dict[str, Any]:
        tracker = SliceTracker(snapshot, list(pending))
        candidates = self._near_empty_nodes(snapshot)
        out: Dict[str, Any] = {
            "proposals": [],
            "predicted_idle_savings_chip_seconds": 0.0,
            "validated": False,
            "near_empty_nodes": [name for name, _ in candidates],
        }
        if tracker.empty or not candidates or not before:
            return out
        # Warm the pool before forking (base-preserving contract).
        snapshot.free_slice_resources()
        snapshot.fork()
        try:
            proposals: List[Dict[str, Any]] = []
            nodes = snapshot.get_nodes()
            for name, _free in candidates:
                if len(proposals) >= self.max_proposals:
                    break
                node = nodes[name]
                accelerator = getattr(node.partitionable, "accelerator", "")
                lacking = tracker.lacking_totals(accelerator)
                if not lacking:
                    continue
                geometry_before = {
                    board: dict(g)
                    for board, g in node.partitionable.geometry().items()
                }
                if not snapshot.update_geometry_for(name, lacking):
                    continue
                geometry_after = {
                    board: dict(g)
                    for board, g in nodes[name].partitionable.geometry().items()
                }
                proposals.append(
                    {
                        "node": name,
                        "geometry_before": geometry_before,
                        "geometry_after": geometry_after,
                        "toward": dict(sorted(lacking.items())),
                    }
                )
            if not proposals:
                return out
            after = self.engine.forecast(
                snapshot,
                list(pending),
                now,
                clocks=clocks,
                cycle_seconds=cycle_seconds,
                reconfig_seconds=reconfig_seconds,
                with_backfill=False,
            ).gangs
        finally:
            snapshot.revert()
        after_by_key = {g.gang: g for g in after}
        savings = 0.0
        regressed = False
        per_gang: List[Dict[str, Any]] = []
        for base in before:
            shadow = after_by_key.get(base.gang)
            if shadow is None:
                continue
            if _STAGE_RANK[shadow.stage] > _STAGE_RANK[base.stage]:
                regressed = True
            gang_chips = self._gang_pending_chips(pending, base)
            saved = 0.0
            if (
                base.eta_seconds is not None
                and shadow.eta_seconds is not None
            ):
                saved = max(0.0, base.eta_seconds - shadow.eta_seconds)
            elif base.eta_seconds is None and shadow.eta_seconds is not None:
                # From un-forecastable (blocked, no hints) to a concrete
                # ETA: credit the wait so far as the saved idle time.
                saved = max(base.wait_seconds or 0.0, cycle_seconds)
            savings += saved * gang_chips
            per_gang.append(
                {
                    "gang": base.gang,
                    "stage_before": base.stage,
                    "stage_after": shadow.stage,
                    "eta_before": base.eta_seconds,
                    "eta_after": shadow.eta_seconds,
                    "saved_chip_seconds": saved * gang_chips,
                }
            )
        out["proposals"] = proposals
        out["predicted_idle_savings_chip_seconds"] = savings
        out["validated"] = bool(proposals) and savings > 0.0 and not regressed
        out["gangs"] = per_gang
        return out

    def _near_empty_nodes(self, snapshot: ClusterSnapshot):
        """(name, free chips) of non-frozen nodes whose free fraction is at
        or above the threshold, most free first.

        Free is measured against BOARD capacity, not carved free slices:
        free_slices() reports only already-carved slices, so a pristine
        (uncarved) node — the advisor's prime re-carve candidate — would
        read as zero free and never be proposed."""
        out = []
        nodes = snapshot.get_nodes()
        for name in sorted(nodes):
            node = nodes[name]
            if getattr(node, "frozen", False):
                continue
            used = sum(_pod_chips(p) for p in node.pods)
            boards = getattr(node.partitionable, "boards", None)
            if boards:
                total = sum(b.chips for b in boards)
                free = total - used
            else:
                free = _free_chips(node)
                total = free + used
            if total <= 0 or free <= 0:
                continue
            if free / total >= self.free_fraction:
                out.append((name, free))
        out.sort(key=lambda item: (-item[1], item[0]))
        return out

    @staticmethod
    def _gang_pending_chips(pending, forecast: GangForecast) -> int:
        names = set(forecast.pending)
        return sum(
            _pod_chips(p) for p in pending if p.namespaced_name in names
        )
