"""Forecast-accuracy calibration: the gate that makes ETAs trustworthy.

Every published gang forecast is stamped (flight recorder + an
in-memory outstanding map); when the capacity ledger observes the gang
actually binding, the forecast joins against the observed bind time and
the error lands here. The tracker publishes p50/p95 of the absolute ETA
error and of the error normalized by the gang's actual total wait — the
acceptance number ("p95 absolute ETA error <= 25% of actual wait") a
later PR will require before letting forecasts actuate backfill.

Deterministic by construction: nearest-rank percentiles over a bounded
sample window, no wall clock, plain float arithmetic — so a replay that
re-feeds the recorded outcomes recomputes the calibration payload
bit-exactly (the "auditor clean on replay" check in record/replay.py).
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

# Bounded sample window: calibration tracks the recent regime (reconfig
# rates and workloads drift), and a bound keeps percentile cost O(1)-ish.
DEFAULT_WINDOW = 512


def nearest_rank(sorted_values: List[float], quantile: float) -> float:
    """Nearest-rank percentile (deterministic, no interpolation): the
    ceil(q*n)-th smallest value, 1-indexed."""
    if not sorted_values:
        return 0.0
    n = len(sorted_values)
    rank = int(quantile * n)
    if rank * 1.0 < quantile * n:  # ceil without float math surprises
        rank += 1
    rank = min(max(rank, 1), n)
    return sorted_values[rank - 1]


class CalibrationTracker:
    """Rolling forecast-vs-observed calibration over the last N gang
    binds. ``add`` takes one joined outcome; ``payload`` is the exported
    calibration block (also the replay comparison payload — keep it a
    pure function of the add() history)."""

    def __init__(self, window: int = DEFAULT_WINDOW) -> None:
        self._samples: deque = deque(maxlen=window)
        self.joined = 0  # outcomes with a usable ETA
        self.unforecast = 0  # gang bound while its ETA was None

    def add(
        self,
        eta_seconds: Optional[float],
        actual_seconds: float,
        wait_seconds: float,
        stage: str = "",
    ) -> Optional[Dict[str, float]]:
        """Join one gang-bound observation against its last forecast.
        ``actual_seconds`` is the observed remaining time from the
        forecast stamp to the bind; ``wait_seconds`` the gang's total
        arrival->bound wait (the normalizer). Returns the sample entry,
        or None when the forecast had no ETA to score."""
        if eta_seconds is None:
            self.unforecast += 1
            return None
        error = abs(eta_seconds - actual_seconds)
        ratio = error / wait_seconds if wait_seconds > 0 else 0.0
        sample = {
            "error_seconds": error,
            "ratio": ratio,
            "stage": stage,
        }
        self._samples.append(sample)
        self.joined += 1
        return sample

    def payload(self) -> Dict[str, Any]:
        errors = sorted(s["error_seconds"] for s in self._samples)
        ratios = sorted(s["ratio"] for s in self._samples)
        # None (not 0.0) when the window is empty: a zero here would
        # read as "perfectly calibrated" with no evidence at all.
        return {
            "samples": len(self._samples),
            "joined": self.joined,
            "unforecast": self.unforecast,
            "p50_error_seconds": nearest_rank(errors, 0.50) if errors else None,
            "p95_error_seconds": nearest_rank(errors, 0.95) if errors else None,
            "p50_ratio": nearest_rank(ratios, 0.50) if ratios else None,
            "p95_ratio": nearest_rank(ratios, 0.95) if ratios else None,
        }
