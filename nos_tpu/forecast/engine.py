"""Forward-simulation forecast engine: fork-based what-if trials.

The engine answers three questions against a planning snapshot, without
ever mutating it (every trial runs inside a CoW fork that is reverted
before returning, the same journal machinery the planner's own carve
trials use):

- **earliest feasible start** per pending gang: can the whole gang place
  on current geometry (``feasible-now``), does it place only after a
  re-carve (``recarve``, with the minimal re-carve node set and a cost
  derived from the measured reconfig rate), or is it ``blocked`` on
  chips bound pods currently hold (with the blocking set, each entry
  linked to the diagnosis ledger via /debug/explain);
- **backfill safety** per (small pending pod, candidate node) pair: the
  exact predicate a gang-aware backfill will enforce — taking that
  placement must not delay the oldest pending gang's ETA;
- the **defrag advisor**'s inputs (see :mod:`nos_tpu.forecast.advisor`).

Everything here is deterministic for a fixed (snapshot, pending, now):
all iteration orders are sorted, caps are applied after sorting, and no
wall clock is ever read — callers supply ``now``. That is what lets two
bench runs at the same seed produce byte-identical forecasts and lets
the accuracy auditor replay calibration bit-exactly.

It reuses the caller-owned planner (its OWN instance, never the live
control loop's) so the version-keyed verdict/futility/node-info memos
stay warm across forecast cycles exactly as they do across plan cycles.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from nos_tpu.kube.objects import Pod
from nos_tpu.partitioning.core.snapshot import ClusterSnapshot
from nos_tpu.partitioning.core.tracker import SliceTracker
from nos_tpu.tpu.topology import topology_chips
from nos_tpu.util import resources as res
from nos_tpu.util.tracing import TRACER

# Forecast stages, ordered best to worst. The order IS the backfill
# predicate: a small placement that moves the oldest gang to a LATER
# stage (or grows its recarve set) is unsafe.
STAGE_FEASIBLE_NOW = "feasible-now"
STAGE_RECARVE = "recarve"
STAGE_BLOCKED = "blocked"
_STAGE_RANK = {STAGE_FEASIBLE_NOW: 0, STAGE_RECARVE: 1, STAGE_BLOCKED: 2}

# Optional workload hint: absolute wall timestamp (seconds) a pod is
# expected to finish by. Blocked-gang ETAs are only computable when the
# blocking pods carry it; without hints the ETA is honestly None.
EXPECTED_COMPLETION_ANNOTATION = "nos.nebuly.com/expected-completion-ts"


def _gang_of(pod: Pod):
    # Lazy import, same reason as the planner's: scheduler.plugins.gang
    # pulls the KubeStore stack.
    from nos_tpu.scheduler.plugins.gang import gang_of

    return gang_of(pod)


def _pod_chips(pod: Pod) -> int:
    return res.tpu_chips_in(res.compute_pod_request(pod))


def _free_chips(node) -> int:
    return sum(
        topology_chips(profile) * qty
        for profile, qty in node.partitionable.free_slices().items()
    )


@dataclass
class GangForecast:
    """One pending gang's earliest-feasible-start classification."""

    gang: str
    size: int
    pending: List[str]  # namespaced names of the still-pending members
    stage: str
    eta_seconds: Optional[float]
    # recarve: the minimal re-carve node set the trial needed (empty for
    # feasible-now; for blocked it is whatever the failed trial touched).
    recarve: List[str] = field(default_factory=list)
    # blocked: bound pods whose chips the gang is waiting on.
    blocking: List[Dict[str, Any]] = field(default_factory=list)
    wait_seconds: Optional[float] = None  # age of the gang's wait clock

    def payload(self) -> Dict[str, Any]:
        return {
            "gang": self.gang,
            "size": self.size,
            "pending": list(self.pending),
            "stage": self.stage,
            "eta_seconds": self.eta_seconds,
            "recarve": list(self.recarve),
            "blocking": [dict(b) for b in self.blocking],
            "wait_seconds": self.wait_seconds,
        }


@dataclass
class BackfillVerdict:
    pod: str
    node: str
    safe: bool
    reason: str

    def payload(self) -> Dict[str, Any]:
        return {
            "pod": self.pod,
            "node": self.node,
            "safe": self.safe,
            "reason": self.reason,
        }


@dataclass
class ForecastResult:
    now: float
    gangs: List[GangForecast]
    backfill: List[BackfillVerdict]
    heatmap: Dict[str, Dict[str, int]]
    advisor: Optional[Dict[str, Any]] = None

    @property
    def unsafe_count(self) -> int:
        return sum(1 for v in self.backfill if not v.safe)

    def payload(self) -> Dict[str, Any]:
        return {
            "now": self.now,
            "gangs": [g.payload() for g in self.gangs],
            "backfill": {
                "safe": sum(1 for v in self.backfill if v.safe),
                "unsafe": self.unsafe_count,
                "pairs": [v.payload() for v in self.backfill],
            },
            "heatmap": {k: dict(v) for k, v in sorted(self.heatmap.items())},
            "advisor": self.advisor,
        }


class ForecastEngine:
    """Pure forecast computation over a snapshot + pending set.

    ``planner`` must be an engine-private Planner (sharing the live
    controller's would clobber its per-plan caches mid-cycle). The
    engine manages that planner's cache lifecycle the way ``plan()``
    does: prune on a retained base, reset on a fresh one.
    """

    def __init__(
        self,
        planner,
        max_gangs: int = 32,
        max_backfill_pairs: int = 64,
        small_pod_chips: int = 2,
        max_blocking: int = 8,
    ) -> None:
        self.planner = planner
        self.max_gangs = max_gangs
        self.max_backfill_pairs = max_backfill_pairs
        self.small_pod_chips = small_pod_chips
        self.max_blocking = max_blocking

    # ------------------------------------------------------------ entry

    def forecast(
        self,
        snapshot: ClusterSnapshot,
        pending: List[Pod],
        now: float,
        clocks: Optional[Dict[str, Dict[str, float]]] = None,
        cycle_seconds: float = 1.0,
        reconfig_seconds: float = 0.5,
        with_backfill: bool = True,
    ) -> ForecastResult:
        """Classify every pending gang and (optionally) every small-pod
        backfill pair. The snapshot is returned to the caller bit-exactly
        as received: trials run in a fork reverted before returning."""
        planner = self.planner
        if snapshot is getattr(planner, "_cache_snapshot", None):
            planner._prune_plan_caches(snapshot, pending)
        else:
            planner._reset_plan_caches(snapshot)
        clocks = clocks or {}
        # Warm the incremental free pool BEFORE forking — fork checkpoints
        # the pool as-is and a None checkpoint would make revert throw the
        # base's pool away (the base-preserving plan() contract).
        snapshot.free_slice_resources()
        gangs = self._gang_groups(pending)
        results: List[GangForecast] = []
        with TRACER.span("forecast.gangs", gangs=len(gangs)):
            for key, (size, members) in gangs[: self.max_gangs]:
                results.append(
                    self._classify_gang(
                        snapshot,
                        key,
                        size,
                        members,
                        now,
                        clocks,
                        cycle_seconds,
                        reconfig_seconds,
                    )
                )
        backfill: List[BackfillVerdict] = []
        heatmap: Dict[str, Dict[str, int]] = {}
        if with_backfill and results:
            with TRACER.span("forecast.backfill"):
                backfill, heatmap = self._backfill_safety(
                    snapshot,
                    pending,
                    gangs,
                    results,
                    now,
                    clocks,
                    cycle_seconds,
                    reconfig_seconds,
                )
        return ForecastResult(
            now=now, gangs=results, backfill=backfill, heatmap=heatmap
        )

    # ------------------------------------------------------ gang grouping

    def _gang_groups(
        self, pending: List[Pod]
    ) -> List[Tuple[str, Tuple[int, List[Pod]]]]:
        """Pending gangs as (key, (declared size, pending members)),
        oldest arrival first via the wait clocks the caller resolves —
        here the deterministic fallback order is (key,) so the cap and
        the "oldest gang" pick never depend on dict order."""
        groups: Dict[str, Tuple[int, List[Pod]]] = {}
        for pod in pending:
            gang = _gang_of(pod)
            if not gang:
                continue
            key, size = gang
            entry = groups.setdefault(key, (size, []))
            entry[1].append(pod)
        out = []
        for key in sorted(groups):
            size, members = groups[key]
            members.sort(key=lambda p: (-_pod_chips(p), p.namespaced_name))
            out.append((key, (size, members)))
        return out

    # ------------------------------------------------- stage classification

    def _classify_gang(
        self,
        snapshot: ClusterSnapshot,
        key: str,
        size: int,
        members: List[Pod],
        now: float,
        clocks: Dict[str, Dict[str, float]],
        cycle_seconds: float,
        reconfig_seconds: float,
    ) -> GangForecast:
        clock = clocks.get(key)
        wait = max(0.0, now - clock["arrival"]) if clock else None
        feasible, _ = self._claim_trial(snapshot, members)
        if feasible:
            return GangForecast(
                gang=key,
                size=size,
                pending=[p.namespaced_name for p in members],
                stage=STAGE_FEASIBLE_NOW,
                # Earliest start = the next plan/bind cycle.
                eta_seconds=cycle_seconds,
                wait_seconds=wait,
            )
        placed_all, recarve = self._carve_trial(snapshot, members)
        if placed_all:
            # Agents actuate a plan's node re-carves concurrently, so the
            # wall cost is one measured reconfig latency (not count *
            # rate) on top of the cycle that applies the plan.
            eta = cycle_seconds + (reconfig_seconds if recarve else 0.0)
            return GangForecast(
                gang=key,
                size=size,
                pending=[p.namespaced_name for p in members],
                stage=STAGE_RECARVE,
                eta_seconds=eta,
                recarve=recarve,
                wait_seconds=wait,
            )
        blocking, eta = self._blocking_set(
            snapshot, members, now, cycle_seconds
        )
        return GangForecast(
            gang=key,
            size=size,
            pending=[p.namespaced_name for p in members],
            stage=STAGE_BLOCKED,
            eta_seconds=eta,
            recarve=recarve,
            blocking=blocking,
            wait_seconds=wait,
        )

    def _claim_trial(
        self, snapshot: ClusterSnapshot, members: List[Pod]
    ) -> Tuple[bool, List[str]]:
        """Can every pending member place on CURRENT geometry (no carve)?
        Returns (all placed, nodes used)."""
        planner = self.planner
        snapshot.fork()
        try:
            used: List[str] = []
            for pod in members:
                claims = planner._claims_free_slices(pod)
                placed_on = None
                for node_name in planner._candidate_nodes(snapshot):
                    if claims and not snapshot.node_has_free_slices(node_name):
                        continue
                    if planner._try_add_pod(snapshot, node_name, pod):
                        placed_on = node_name
                        break
                if placed_on is None:
                    return False, used
                used.append(placed_on)
            return True, used
        finally:
            snapshot.revert()

    def _carve_trial(
        self, snapshot: ClusterSnapshot, members: List[Pod]
    ) -> Tuple[bool, List[str]]:
        """Does the gang place after re-carving? Returns (all placed,
        minimal re-carve node set = nodes whose geometry the successful
        trial actually changed)."""
        planner = self.planner
        snapshot.fork()
        try:
            tracker = SliceTracker(snapshot, members)
            placed = planner._plan_pass(snapshot, tracker, members, quiet=True)
            placed_names = {p.namespaced_name for p in placed}
            all_placed = all(
                p.namespaced_name in placed_names for p in members
            )
            # The trial's inner commits folded into our fork's journal:
            # every touched node has its pre-fork clone there, so the
            # re-carve set is exactly the touched nodes whose geometry
            # (not just pod placements) differs from the backup.
            journal = snapshot._journals[-1]
            nodes = snapshot.get_nodes()
            recarve = [
                name
                for name in sorted(journal)
                if name in nodes
                and nodes[name].partitionable.geometry()
                != journal[name].partitionable.geometry()
            ]
            return all_placed, recarve
        finally:
            snapshot.revert()

    def _blocking_set(
        self,
        snapshot: ClusterSnapshot,
        members: List[Pod],
        now: float,
        cycle_seconds: float,
    ) -> Tuple[List[Dict[str, Any]], Optional[float]]:
        """Bound pods whose chips the gang is waiting on, earliest
        expected completion first: the gang binds when the earliest
        sufficient set frees, so picking long-running blockers would
        systematically overprice the ETA (hintless pods sort last — they
        cannot be priced either way). The ETA is only computable when
        every chosen blocker carries the expected-completion hint."""
        needed = sum(_pod_chips(p) for p in members)
        nodes = snapshot.get_nodes()
        candidates: List[Any] = []
        for name in sorted(nodes):
            for pod in nodes[name].pods:
                chips = _pod_chips(pod)
                if chips <= 0:
                    continue
                hint = pod.metadata.annotations.get(
                    EXPECTED_COMPLETION_ANNOTATION
                )
                completion: Optional[float] = None
                if hint is not None:
                    try:
                        completion = float(hint)
                    except ValueError:
                        completion = None
                candidates.append((completion, name, pod, chips))
        candidates.sort(
            key=lambda c: (
                c[0] is None,
                c[0] if c[0] is not None else 0.0,
                c[2].namespaced_name,
            )
        )
        blocking: List[Dict[str, Any]] = []
        covered = 0
        latest_completion: Optional[float] = 0.0
        for completion, name, pod, chips in candidates:
            if covered >= needed or len(blocking) >= self.max_blocking:
                break
            entry = {
                "pod": pod.namespaced_name,
                "node": name,
                "chips": chips,
                "explain": f"/debug/explain?pod={pod.namespaced_name}",
            }
            if completion is not None:
                entry["expected_completion_ts"] = completion
                if latest_completion is not None:
                    latest_completion = max(latest_completion, completion)
            else:
                latest_completion = None
            blocking.append(entry)
            covered += chips
        eta: Optional[float] = None
        if blocking and latest_completion is not None and covered >= needed:
            # Chips free when the slowest blocker finishes; the next plan
            # cycle after that binds the gang.
            eta = max(0.0, latest_completion - now) + cycle_seconds
        return blocking, eta

    # -------------------------------------------------- backfill predicate

    def _backfill_safety(
        self,
        snapshot: ClusterSnapshot,
        pending: List[Pod],
        gangs,
        gang_results: List[GangForecast],
        now: float,
        clocks: Dict[str, Dict[str, float]],
        cycle_seconds: float,
        reconfig_seconds: float,
    ) -> Tuple[List[BackfillVerdict], Dict[str, Dict[str, int]]]:
        """The exact predicate gang-aware backfill will enforce: place the
        small pod on the candidate node in a fork, re-classify the OLDEST
        pending gang, and call the pair unsafe when its stage worsens or
        its re-carve set grows."""
        oldest = self._oldest_gang(gangs, gang_results, clocks)
        if oldest is None:
            return [], {}
        oldest_key, oldest_size, oldest_members, baseline = oldest
        planner = self.planner
        small = sorted(
            (
                p
                for p in pending
                if not _gang_of(p)
                and 0 < _pod_chips(p) <= self.small_pod_chips
            ),
            key=lambda p: p.namespaced_name,
        )
        verdicts: List[BackfillVerdict] = []
        heatmap: Dict[str, Dict[str, int]] = {}
        for pod in small:
            if len(verdicts) >= self.max_backfill_pairs:
                break
            claims = planner._claims_free_slices(pod)
            for node_name in planner._candidate_nodes(snapshot):
                if len(verdicts) >= self.max_backfill_pairs:
                    break
                if claims and not snapshot.node_has_free_slices(node_name):
                    continue
                snapshot.fork()
                try:
                    if not planner._try_add_pod(snapshot, node_name, pod):
                        continue  # not a candidate slice for this pod
                    after = self._classify_gang(
                        snapshot,
                        oldest_key,
                        oldest_size,
                        oldest_members,
                        now,
                        clocks,
                        cycle_seconds,
                        reconfig_seconds,
                    )
                finally:
                    snapshot.revert()
                safe, reason = self._compare(baseline, after)
                verdicts.append(
                    BackfillVerdict(
                        pod=pod.namespaced_name,
                        node=node_name,
                        safe=safe,
                        reason=reason,
                    )
                )
                cell = heatmap.setdefault(node_name, {"safe": 0, "unsafe": 0})
                cell["safe" if safe else "unsafe"] += 1
        return verdicts, heatmap

    @staticmethod
    def _oldest_gang(gangs, gang_results, clocks):
        """(key, size, members, baseline forecast) for the gang backfill
        must protect: longest wait first, gang key as the deterministic
        tie-break (also the no-clocks fallback order)."""
        if not gang_results:
            return None
        by_key = {key: entry for key, entry in gangs}
        best = min(
            gang_results,
            key=lambda g: (-(g.wait_seconds or 0.0), g.gang),
        )
        size, members = by_key[best.gang]
        return best.gang, size, members, best

    @staticmethod
    def _compare(
        before: GangForecast, after: GangForecast
    ) -> Tuple[bool, str]:
        if _STAGE_RANK[after.stage] > _STAGE_RANK[before.stage]:
            return False, (
                f"oldest gang {before.gang} degrades "
                f"{before.stage} -> {after.stage}"
            )
        if (
            after.stage == STAGE_RECARVE
            and before.stage == STAGE_RECARVE
            and len(after.recarve) > len(before.recarve)
        ):
            return False, (
                f"oldest gang {before.gang} re-carve set grows "
                f"{len(before.recarve)} -> {len(after.recarve)}"
            )
        if (
            before.eta_seconds is not None
            and after.eta_seconds is not None
            and after.eta_seconds > before.eta_seconds
        ):
            return False, (
                f"oldest gang {before.gang} ETA grows "
                f"{before.eta_seconds:.3f}s -> {after.eta_seconds:.3f}s"
            )
        return True, ""
