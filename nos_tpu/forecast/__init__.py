"""Placement forecasting: earliest-feasible-start ETAs per pending gang,
backfill-safety classification, and a read-only defrag advisor — the
observability layer ROADMAP item 2's gang-aware backfill builds on."""
from nos_tpu.forecast.accuracy import CalibrationTracker, nearest_rank
from nos_tpu.forecast.advisor import DefragAdvisor
from nos_tpu.forecast.engine import (
    EXPECTED_COMPLETION_ANNOTATION,
    STAGE_BLOCKED,
    STAGE_FEASIBLE_NOW,
    STAGE_RECARVE,
    BackfillVerdict,
    ForecastEngine,
    ForecastResult,
    GangForecast,
)
from nos_tpu.forecast.forecaster import PlacementForecaster

__all__ = [
    "BackfillVerdict",
    "CalibrationTracker",
    "DefragAdvisor",
    "EXPECTED_COMPLETION_ANNOTATION",
    "ForecastEngine",
    "ForecastResult",
    "GangForecast",
    "PlacementForecaster",
    "STAGE_BLOCKED",
    "STAGE_FEASIBLE_NOW",
    "STAGE_RECARVE",
    "nearest_rank",
]
