from nos_tpu.sim.kubelet import SimKubelet

__all__ = ["SimKubelet"]
