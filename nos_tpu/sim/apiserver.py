"""In-process Kubernetes apiserver stub for API-backend tests.

The reference's integration suites boot a real etcd+apiserver via envtest
(/root/reference/internal/controllers/elasticquota/suite_int_test.go:56-63).
This image has no cluster binaries, so the same role is played by a real
HTTP server (ThreadingHTTPServer on loopback) implementing the apiserver
wire subset the suite speaks: CRUD with resourceVersion bookkeeping and
optimistic-concurrency conflicts, namespaced + all-namespace routes, and
chunked streaming watches. KubeApiClient/KubeApiStore talk to it over the
exact code path they use against a production apiserver.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

_PREFIXES = ("/api/v1", "/apis/policy/v1", "/apis/nos.nebuly.com/v1alpha1")

_PLURAL_TO_KIND = {
    "pods": "Pod",
    "nodes": "Node",
    "configmaps": "ConfigMap",
    "services": "Service",
    "events": "Event",
    "poddisruptionbudgets": "PodDisruptionBudget",
    "elasticquotas": "ElasticQuota",
    "compositeelasticquotas": "CompositeElasticQuota",
}


class _State:
    def __init__(self) -> None:
        self.lock = threading.Condition()
        self.rv = 0
        self.uid = 0
        # (plural, ns, name) -> wire object
        self.objects: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        # append-only event log: (rv, type, plural, wire object)
        self.events: List[Tuple[int, str, str, Dict[str, Any]]] = []

    def bump(self) -> int:
        self.rv += 1
        return self.rv

    def record(self, etype: str, plural: str, obj: Dict[str, Any]) -> None:
        self.events.append((int(obj["metadata"]["resourceVersion"]), etype, plural, obj))
        self.lock.notify_all()


class StubApiServer:
    """`with StubApiServer() as s: KubeApiClient(creds(s.url))`."""

    def __init__(self, disabled_plurals=()) -> None:
        self.state = _State()
        state = self.state
        disabled = set(disabled_plurals)  # simulate uninstalled CRDs (404)

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            # -------------------------------------------------- plumbing
            def _send_json(self, code: int, payload: Dict[str, Any]) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _error(self, code: int, reason: str, message: str = "") -> None:
                self._send_json(
                    code,
                    {
                        "kind": "Status",
                        "status": "Failure",
                        "code": code,
                        "reason": reason,
                        "message": message or reason,
                    },
                )

            def _route(self):
                """path -> (plural, namespace, name, subresource, query)."""
                path, _, query = self.path.partition("?")
                params = {}
                if query:
                    for part in query.split("&"):
                        k, _, v = part.partition("=")
                        params[k] = v
                for prefix in _PREFIXES:
                    if path.startswith(prefix + "/"):
                        rest = [p for p in path[len(prefix):].split("/") if p]
                        if not rest:
                            return None
                        if rest[0] == "namespaces" and len(rest) >= 3:
                            ns, plural = rest[1], rest[2]
                            name = rest[3] if len(rest) > 3 else ""
                            sub = rest[4] if len(rest) > 4 else ""
                        else:
                            plural = rest[0]
                            ns = ""
                            name = rest[1] if len(rest) > 1 else ""
                            sub = rest[2] if len(rest) > 2 else ""
                        if plural in _PLURAL_TO_KIND and plural not in disabled:
                            return plural, ns, name, sub, params
                return None

            def _read_body(self) -> Dict[str, Any]:
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n)) if n else {}

            def _drain_body(self) -> None:
                """Consume an unread request body before replying early.

                Responding without reading the body leaves its bytes in the
                keep-alive stream; the NEXT request on the connection then
                parses as body-garbage + request-line ("Bad request
                syntax"), poisoning an innocent caller. Every reply path
                that fires before _read_body() must drain first."""
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    try:
                        self.rfile.read(n)
                    except (OSError, ValueError):
                        self.close_connection = True

            def _fault_gate(self) -> bool:
                """Consult the armed chaos injector (if any) before serving.

                The injector is duck-typed (`on_request(method, path)` →
                None to proceed, or `(code, reason)` to deny; it may sleep
                internally to model latency). Production paths never pay
                for this: one getattr against a None default.
                """
                fault = getattr(self.server, "chaos_faults", None)
                if fault is None:
                    return False
                verdict = fault.on_request(self.command, self.path)
                if verdict is None:
                    return False
                code, reason = verdict
                self._drain_body()
                try:
                    self._error(code, reason, "chaos fault injection")
                except (OSError, ValueError):
                    pass
                return True

            # ------------------------------------------------------ verbs
            def do_GET(self) -> None:
                if self._fault_gate():
                    return
                route = self._route()
                if not route:
                    return self._error(404, "NotFound", self.path)
                plural, ns, name, sub, params = route
                if name:
                    with state.lock:
                        obj = state.objects.get((plural, ns, name))
                    if obj is None:
                        return self._error(404, "NotFound", f"{plural} {ns}/{name}")
                    return self._send_json(200, obj)
                if params.get("watch") == "true":
                    return self._watch(plural, ns, params)
                with state.lock:
                    items = [
                        o
                        for (p, o_ns, _), o in sorted(state.objects.items())
                        if p == plural and (not ns or o_ns == ns)
                    ]
                    rv = state.rv
                return self._send_json(
                    200,
                    {
                        "kind": _PLURAL_TO_KIND[plural] + "List",
                        "metadata": {"resourceVersion": str(rv)},
                        "items": items,
                    },
                )

            def do_POST(self) -> None:
                if self._fault_gate():
                    return
                route = self._route()
                if not route:
                    self._drain_body()
                    return self._error(404, "NotFound", self.path)
                plural, ns, name, sub, _ = route
                if sub == "binding":
                    return self._bind(plural, ns, name)
                obj = self._read_body()
                meta = obj.setdefault("metadata", {})
                if ns:
                    meta["namespace"] = ns
                name = meta.get("name", "")
                key = (plural, meta.get("namespace", ""), name)
                with state.lock:
                    if key in state.objects:
                        return self._error(
                            409, "AlreadyExists", f"{plural} {name} already exists"
                        )
                    state.uid += 1
                    meta.setdefault("uid", f"stub-uid-{state.uid}")
                    meta.setdefault(
                        "creationTimestamp",
                        time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                    )
                    meta["resourceVersion"] = str(state.bump())
                    state.objects[key] = obj
                    state.record("ADDED", plural, obj)
                self._send_json(201, obj)

            def do_PUT(self) -> None:
                if self._fault_gate():
                    return
                route = self._route()
                if not route or not route[2]:
                    self._drain_body()
                    return self._error(404, "NotFound", self.path)
                plural, ns, name, _, _ = route
                obj = self._read_body()
                meta = obj.setdefault("metadata", {})
                key = (plural, meta.get("namespace", ns), name)
                with state.lock:
                    current = state.objects.get(key)
                    if current is None:
                        return self._error(404, "NotFound", f"{plural} {ns}/{name}")
                    sent_rv = str(meta.get("resourceVersion") or "")
                    cur_rv = str(current["metadata"]["resourceVersion"])
                    if sent_rv and sent_rv != cur_rv:
                        return self._error(
                            409,
                            "Conflict",
                            f"operation cannot be fulfilled: object modified "
                            f"(have {sent_rv}, want {cur_rv})",
                        )
                    meta["uid"] = current["metadata"].get("uid", "")
                    meta.setdefault(
                        "creationTimestamp", current["metadata"].get("creationTimestamp")
                    )
                    meta["resourceVersion"] = str(state.bump())
                    state.objects[key] = obj
                    state.record("MODIFIED", plural, obj)
                self._send_json(200, obj)

            def _bind(self, plural: str, ns: str, name: str) -> None:
                """POST …/pods/{name}/binding — the real bind verb."""
                body = self._read_body()
                target = (body.get("target") or {}).get("name", "")
                if plural != "pods" or not target:
                    return self._error(400, "BadRequest", "invalid binding")
                with state.lock:
                    obj = state.objects.get((plural, ns, name))
                    if obj is None:
                        return self._error(404, "NotFound", f"{plural} {ns}/{name}")
                    if (obj.get("spec") or {}).get("nodeName"):
                        return self._error(
                            409, "Conflict", "pod is already assigned to a node"
                        )
                    obj.setdefault("spec", {})["nodeName"] = target
                    obj["metadata"]["resourceVersion"] = str(state.bump())
                    state.record("MODIFIED", plural, obj)
                self._send_json(201, {"kind": "Status", "status": "Success"})

            def _merge_apply(self, target: Dict[str, Any], patch: Dict[str, Any]) -> None:
                for k, v in patch.items():
                    if v is None:
                        target.pop(k, None)
                    elif isinstance(v, dict) and isinstance(target.get(k), dict):
                        self._merge_apply(target[k], v)
                    else:
                        target[k] = v

            def do_PATCH(self) -> None:
                if self._fault_gate():
                    return
                route = self._route()
                if not route or not route[2]:
                    self._drain_body()
                    return self._error(404, "NotFound", self.path)
                plural, ns, name, sub, _ = route
                if "merge-patch" not in (self.headers.get("Content-Type") or ""):
                    self._drain_body()
                    return self._error(415, "UnsupportedMediaType")
                patch = self._read_body()
                with state.lock:
                    obj = state.objects.get((plural, ns, name))
                    if obj is None:
                        return self._error(404, "NotFound", f"{plural} {ns}/{name}")
                    sent_rv = str(((patch.get("metadata") or {}).get("resourceVersion")) or "")
                    cur_rv = str(obj["metadata"]["resourceVersion"])
                    if sent_rv and sent_rv != cur_rv:
                        return self._error(
                            409, "Conflict",
                            f"object modified (have {sent_rv}, want {cur_rv})",
                        )
                    if sub == "status":
                        # subresource: only the status stanza applies
                        self._merge_apply(
                            obj.setdefault("status", {}), patch.get("status") or {}
                        )
                    elif sub:
                        return self._error(404, "NotFound", f"subresource {sub}")
                    else:
                        # main resource: status + immutable fields rejected,
                        # like a real apiserver
                        if "status" in patch and plural != "configmaps":
                            return self._error(
                                422, "Invalid",
                                "status must be updated via the /status subresource",
                            )
                        if (patch.get("spec") or {}).get("nodeName") and plural == "pods":
                            return self._error(
                                422, "Invalid", "spec.nodeName: field is immutable (use binding)"
                            )
                        patch = dict(patch)
                        patch.get("metadata", {}).pop("resourceVersion", None)
                        self._merge_apply(obj, patch)
                    obj["metadata"]["resourceVersion"] = str(state.bump())
                    state.record("MODIFIED", plural, obj)
                self._send_json(200, obj)

            def do_DELETE(self) -> None:
                if self._fault_gate():
                    return
                route = self._route()
                if not route or not route[2]:
                    return self._error(404, "NotFound", self.path)
                plural, ns, name, _, _ = route
                with state.lock:
                    obj = state.objects.pop((plural, ns, name), None)
                    if obj is None:
                        return self._error(404, "NotFound", f"{plural} {ns}/{name}")
                    obj = dict(obj)
                    obj["metadata"] = dict(obj["metadata"])
                    obj["metadata"]["resourceVersion"] = str(state.bump())
                    state.record("DELETED", plural, obj)
                self._send_json(200, obj)

            # ------------------------------------------------------ watch
            def _watch(self, plural: str, ns: str, params: Dict[str, str]) -> None:
                fault = getattr(self.server, "chaos_faults", None)
                since = int(params.get("resourceVersion") or 0)
                deadline = time.monotonic() + float(params.get("timeoutSeconds") or 60)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def send_chunk(payload: Dict[str, Any]) -> bool:
                    data = (json.dumps(payload) + "\n").encode()
                    if fault is not None and fault.take_sever():
                        # Chaos: kill the stream MID-frame — the client
                        # sees the TCP connection die halfway through a
                        # chunk, not a clean end-of-stream.
                        try:
                            self.wfile.write(
                                f"{len(data):x}\r\n".encode() + data[: len(data) // 2]
                            )
                            self.wfile.flush()
                        except (OSError, ValueError):
                            pass
                        try:
                            self.connection.close()
                        except OSError:
                            pass
                        return False
                    try:
                        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                        self.wfile.flush()
                        return True
                    except (OSError, ValueError):
                        # Any socket failure — broken pipe, reset, closed
                        # file object — means the client is gone: end this
                        # watch quietly instead of letting the exception
                        # propagate out of the handler thread.
                        return False

                cursor = since
                last_write = time.monotonic()
                try:
                    while time.monotonic() < deadline:
                        with state.lock:
                            pending = [
                                (rv, et, o)
                                for (rv, et, p, o) in state.events
                                if rv > cursor
                                and p == plural
                                and (not ns or o["metadata"].get("namespace", "") == ns)
                            ]
                            rv_now = state.rv
                            if not pending:
                                state.lock.wait(timeout=0.2)
                        if not pending:
                            # Idle heartbeat: a BOOKMARK keeps the client's
                            # resourceVersion fresh AND probes the socket, so
                            # a disconnected watcher is reaped within ~a
                            # second instead of parking its handler thread
                            # (and re-scanning the event log) until the full
                            # timeoutSeconds deadline.
                            if time.monotonic() - last_write >= 0.5:
                                bookmark = {
                                    "type": "BOOKMARK",
                                    "object": {
                                        "metadata": {"resourceVersion": str(rv_now)}
                                    },
                                }
                                if not send_chunk(bookmark):
                                    return
                                last_write = time.monotonic()
                            continue
                        for rv, etype, obj in pending:
                            cursor = max(cursor, rv)
                            if not send_chunk({"type": etype, "object": obj}):
                                return
                            last_write = time.monotonic()
                    try:  # terminating zero-chunk
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                    except (OSError, ValueError):
                        pass
                except (OSError, ValueError):
                    # Disconnect surfaced outside send_chunk (e.g. while
                    # flushing headers): same story — die quietly.
                    pass

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._server.chaos_faults = None
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="stub-apiserver", daemon=True
        )

    # ------------------------------------------------------------ lifecycle
    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "StubApiServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def set_fault_injector(self, injector) -> None:
        """Arm (or with None, disarm) a chaos fault injector.

        Duck-typed: ``on_request(method, path)`` is consulted before every
        verb (return ``(code, reason)`` to deny, None to proceed; sleep
        inside to model latency) and ``take_sever()`` before every watch
        chunk (return True to cut the stream mid-frame)."""
        self._server.chaos_faults = injector

    def __enter__(self) -> "StubApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # Test convenience: inject/read wire objects directly (an "external
    # client" the store under test doesn't know about).
    def inject(self, plural: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        meta = obj.setdefault("metadata", {})
        key = (plural, meta.get("namespace", ""), meta.get("name", ""))
        with self.state.lock:
            created = key not in self.state.objects
            self.state.uid += 1
            meta.setdefault("uid", f"stub-uid-{self.state.uid}")
            meta["resourceVersion"] = str(self.state.bump())
            self.state.objects[key] = obj
            self.state.record("ADDED" if created else "MODIFIED", plural, obj)
        return obj

    def read(self, plural: str, ns: str, name: str) -> Optional[Dict[str, Any]]:
        with self.state.lock:
            obj = self.state.objects.get((plural, ns, name))
            return json.loads(json.dumps(obj)) if obj else None
