"""SimKubelet: flips bound pods to Running.

The reference relies on real kubelets; in the in-process cluster (tests,
kind-style dry runs, benchmarks) this controller provides the missing
lifecycle edge: a pod bound by the scheduler becomes Running, which in turn
drives quota accounting and device usage reporting.
"""
from __future__ import annotations

from typing import Optional

from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.objects import PodPhase
from nos_tpu.kube.store import KubeStore, NotFoundError


class SimKubelet:
    def __init__(self, store: KubeStore) -> None:
        self.store = store

    def reconcile(self, req: Request) -> Optional[Result]:
        pod = self.store.try_get("Pod", req.name, req.namespace)
        if pod is None:
            return None
        if not pod.spec.node_name or pod.status.phase != PodPhase.PENDING:
            return None

        def mutate(p):
            p.status.phase = PodPhase.RUNNING

        try:
            self.store.patch_merge("Pod", req.name, req.namespace, mutate)
        except NotFoundError:
            pass
        return None
