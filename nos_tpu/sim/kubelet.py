"""SimKubelet: admits bound pods against device truth, then runs them.

The reference relies on real kubelets; in the in-process cluster (tests,
kind-style dry runs, benchmarks) this controller provides the missing
lifecycle edges:

- **Admission**: a real kubelet is the last line of defense against
  scheduler/repartitioner races — it rejects a pod whose devices are not
  actually allocatable (``OutOfcpu``-style terminal failure). Here the
  arbiter is the device layer's slice inventory (ground truth, not the
  node's possibly-lagging allocatable): if the pod's normalized slice
  demand plus that of already-admitted pods exceeds the devices that
  exist, the pod is failed with reason ``OutOfTpu``. Without this, a
  bind racing a re-carve can double-book a board's chips.
- **Running**: an admitted pod becomes Running, which in turn drives
  quota accounting and device usage reporting.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.objects import Pod, PodCondition, PodPhase
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.util import resources as res
from nos_tpu.util.tracing import NOOP_SPAN, TRACER

import contextlib
import logging

log = logging.getLogger("nos_tpu.kubelet")

# node name -> board index -> profile -> count
GeometryFn = Callable[[str], Dict[int, Dict[str, int]]]


class SimKubelet:
    def __init__(self, store: KubeStore, geometry_fn: Optional[GeometryFn] = None) -> None:
        self.store = store
        self.geometry_fn = geometry_fn
        self.admission_rejects = 0

    def reconcile(self, req: Request) -> Optional[Result]:
        pod = self.store.try_get("Pod", req.name, req.namespace)
        if pod is None:
            return None
        if not pod.spec.node_name or pod.status.phase != PodPhase.PENDING:
            return None

        # The journey ended at bind; its trace is already stored. The link
        # the scheduler left lets this post-bind span append to it (the
        # tracer supports late spans on stored traces).
        parent = TRACER.linked(("admit", pod.namespaced_name))
        ctx = (
            TRACER.span("kubelet.admit", parent=parent, node=pod.spec.node_name)
            if parent is not None
            else contextlib.nullcontext(NOOP_SPAN)
        )
        with ctx as span:
            admitted = self._admit(pod)
            span.set_attributes(admitted=admitted)
        if not admitted:
            self.admission_rejects += 1
            log.warning(
                "kubelet: rejecting %s on %s: slice demand exceeds devices "
                "(OutOfTpu)",
                pod.namespaced_name,
                pod.spec.node_name,
            )

            def fail(p):
                p.status.phase = PodPhase.FAILED
                p.status.conditions.append(
                    PodCondition(
                        type="PodScheduled",
                        status="False",
                        reason="OutOfTpu",
                        message="node has no free slice for the pod's request",
                    )
                )

            try:
                self.store.patch_merge("Pod", req.name, req.namespace, fail)
            except NotFoundError:
                pass
            return None

        def mutate(p):
            p.status.phase = PodPhase.RUNNING

        try:
            self.store.patch_merge("Pod", req.name, req.namespace, mutate)
        except NotFoundError:
            pass
        return None

    # ------------------------------------------------------------ admission

    def _admit(self, pod: Pod) -> bool:
        """Slice-denominated admission against the device inventory."""
        if self.geometry_fn is None:
            return True
        node = self.store.try_get("Node", pod.spec.node_name)
        if node is None:
            return True
        if node.metadata.labels.get(labels.PARTITIONING_LABEL) not in (
            labels.PartitioningKind.TPU,
            labels.PartitioningKind.HYBRID,
        ):
            return True
        accelerator = node.metadata.labels.get(labels.GKE_TPU_ACCELERATOR_LABEL, "")
        if not accelerator:
            return True
        demand = self._slice_demand(pod, accelerator)
        if not demand:
            return True  # no slice resources involved (e.g. sharing mode)
        for other in self.store.list("Pod"):
            if other.spec.node_name != pod.spec.node_name:
                continue
            if other.namespaced_name == pod.namespaced_name:
                continue
            # Already-admitted pods hold their devices.
            if other.status.phase != PodPhase.RUNNING:
                continue
            for profile, qty in self._slice_demand(other, accelerator).items():
                demand[profile] = demand.get(profile, 0) + qty
        inventory: Dict[str, int] = {}
        try:
            for board in self.geometry_fn(pod.spec.node_name).values():
                for profile, qty in board.items():
                    inventory[profile] = inventory.get(profile, 0) + qty
        except Exception:  # device layer unavailable: fail open
            return True
        return all(inventory.get(p, 0) >= q for p, q in demand.items())

    @staticmethod
    def _slice_demand(pod: Pod, accelerator: str) -> Dict[str, int]:
        request = res.normalize_tpu_request(res.compute_pod_request(pod), accelerator)
        return {
            constants.tpu_slice_topology(name): int(qty)
            for name, qty in request.items()
            if constants.is_tpu_slice_resource(name)
        }
