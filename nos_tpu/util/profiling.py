"""Sampling wall-clock profiler for the control-plane threads.

The suite observes everything outward (journeys, decisions, chip-seconds)
but nothing inward: nothing answered "where does a planner cycle's wall
time actually go". This module is the dependency-free answer — a
background sampler over ``sys._current_frames()`` designed to stay ON in a
long-running scheduler:

- **Registered threads only.** Controller loops register their thread id
  (``PROFILER.register_thread()`` in the thread body, or the
  ``registered()`` context manager); everything else — JAX worker pools,
  HTTP handler threads, the sampler itself — is invisible, so sample
  volume tracks the control plane, not the process.
- **Bounded aggregation.** Samples collapse into a
  ``(thread, phase, stack) -> count`` table capped at ``max_stacks``
  distinct entries; overflow increments a drop counter instead of growing
  memory. Frames are ``file.py:function`` (no line numbers), keeping the
  key space small and the flamegraph readable.
- **Phase attribution.** Each sample is labeled with the thread's
  innermost active tracing span via ``tracing.current_phase`` — the
  thread-id → span registry maintained by ``Tracer.span``/``attach``
  enter/exit. A bench_planner cycle therefore decomposes into
  ``planner.plan`` / ``snapshot.take`` / ``partitioner.actuate`` … with no
  instrumentation beyond the spans the code already has. (Attribution
  requires ``TRACER.enabled``; with tracing off every sample lands in
  ``(no-phase)``.)
- **Measured overhead.** The sampler accounts its own duty cycle
  (time capturing / wall time enabled) into
  ``nos_tpu_profiler_overhead_fraction`` — the acceptance budget is <= 2%
  at the default 100 Hz rate, and the slow guard in
  ``tests/partitioning/test_planner_perf.py`` enforces it.

Surfaces: ``/debug/profile`` (bearer-gated; JSON top-N self-time by
default, ``?format=collapsed`` for flamegraph.pl/speedscope collapsed
stacks, ``?action=start|stop`` for runtime on/off) and
``bench_planner --profile`` (the committed offline artifact).
"""
from __future__ import annotations

import contextlib
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from nos_tpu.util import metrics, tracing


class StackProfiler:
    """Aggregating sampler over ``sys._current_frames()``.

    Thread-safe throughout: registration, sampling, rendering, and
    start/stop may race freely (start/stop are idempotent; the stop path
    joins the sampler thread before returning).
    """

    DEFAULT_INTERVAL = 0.01  # 100 Hz
    MAX_STACKS = 2048
    MAX_DEPTH = 48

    def __init__(self, interval_seconds: float = DEFAULT_INTERVAL) -> None:
        self.interval = interval_seconds
        self.max_stacks = self.MAX_STACKS
        self.max_depth = self.MAX_DEPTH
        self._lock = threading.Lock()
        self._threads: Dict[int, str] = {}
        # code object -> "file.py:func", touched only by the sampler; keyed
        # on the code object itself (ids recycle), bounded by a flush.
        self._frame_labels: Dict[Any, str] = {}
        # (thread name, phase, root-first stack tuple) -> sample count.
        self._table: Dict[Tuple[str, str, Tuple[str, ...]], int] = {}
        self._phase_samples: Dict[str, int] = {}
        self._total_samples = 0
        self._dropped_stacks = 0
        # Overhead accounting: sampler busy time vs wall time enabled
        # (prior enable windows accumulate into _wall_accum).
        self._busy_s = 0.0
        self._wall_accum = 0.0
        self._started_at = 0.0
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------- registration

    def register_thread(
        self, name: Optional[str] = None, ident: Optional[int] = None
    ) -> int:
        """Opt the thread in to sampling; returns the registered id."""
        if ident is None:
            ident = threading.get_ident()
            name = name or threading.current_thread().name
        with self._lock:
            self._threads[ident] = name or str(ident)
        return ident

    def unregister_thread(self, ident: Optional[int] = None) -> None:
        if ident is None:
            ident = threading.get_ident()
        with self._lock:
            self._threads.pop(ident, None)

    @contextlib.contextmanager
    def registered(self, name: Optional[str] = None):
        """Register the calling thread for the duration of the block."""
        ident = self.register_thread(name)
        try:
            yield self
        finally:
            self.unregister_thread(ident)

    def threads(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._threads)

    # ---------------------------------------------------------- lifecycle

    @property
    def enabled(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    def start(self, interval_seconds: Optional[float] = None) -> bool:
        """Start the sampler thread; returns False if already running."""
        with self._lock:
            if interval_seconds is not None:
                self.interval = interval_seconds
            if self._thread is not None and self._thread.is_alive():
                return False
            stop = threading.Event()
            thread = threading.Thread(
                target=self._run, args=(stop,), name="stack-profiler", daemon=True
            )
            self._stop = stop
            self._thread = thread
            self._started_at = time.perf_counter()
            # Started under the lock: a concurrent stop() that wins the
            # lock next must only ever see a thread that is joinable.
            thread.start()
        return True

    def stop(self) -> bool:
        """Stop and join the sampler thread; returns False if not running."""
        with self._lock:
            thread = self._thread
            stop = self._stop
            self._thread = None
            self._stop = None
            if thread is not None:
                self._wall_accum += time.perf_counter() - self._started_at
        if thread is None or stop is None:
            return False
        stop.set()
        thread.join(timeout=2.0)
        return True

    def reset(self) -> None:
        """Drop all samples and overhead accounting (registrations and the
        running sampler, if any, are kept)."""
        with self._lock:
            self._table.clear()
            self._phase_samples.clear()
            self._total_samples = 0
            self._dropped_stacks = 0
            self._busy_s = 0.0
            self._wall_accum = 0.0
            self._started_at = time.perf_counter()

    def _run(self, stop: threading.Event) -> None:
        # Event.wait paces the loop — no hot polling (the Batcher lesson:
        # a fixed-tick busy loop burns a core at idle).
        while not stop.wait(self.interval):
            t0 = time.perf_counter()
            self.sample_once()
            with self._lock:
                self._busy_s += time.perf_counter() - t0
            metrics.PROFILER_OVERHEAD.set(round(self.overhead_fraction(), 6))

    # ----------------------------------------------------------- sampling

    def sample_once(self) -> int:
        """Capture one sample of every registered thread; returns the
        number of threads sampled. Public so tests can sample
        deterministically without the background thread."""
        with self._lock:
            targets = list(self._threads.items())
        if not targets:
            return 0
        labels = self._frame_labels
        if len(labels) > 8192:  # code churn backstop (reloads, lambdas)
            labels.clear()
        frames = sys._current_frames()
        keys: List[Tuple[str, str, Tuple[str, ...]]] = []
        for ident, name in targets:
            frame = frames.get(ident)
            if frame is None:
                continue
            stack: List[str] = []
            depth = 0
            while frame is not None and depth < self.max_depth:
                code = frame.f_code
                label = labels.get(code)
                if label is None:
                    label = f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}"
                    labels[code] = label
                stack.append(label)
                frame = frame.f_back
                depth += 1
            stack.reverse()  # root-first: collapsed-stack order
            keys.append((name, tracing.current_phase(ident), tuple(stack)))
        del frames  # drop the frame references promptly
        if not keys:
            return 0
        with self._lock:
            for key in keys:
                self._total_samples += 1
                phase = key[1]
                self._phase_samples[phase] = self._phase_samples.get(phase, 0) + 1
                if key in self._table or len(self._table) < self.max_stacks:
                    self._table[key] = self._table.get(key, 0) + 1
                else:
                    self._dropped_stacks += 1
        metrics.PROFILER_SAMPLES.inc(len(keys))
        return len(keys)

    # ---------------------------------------------------------- reporting

    @property
    def total_samples(self) -> int:
        with self._lock:
            return self._total_samples

    def overhead_fraction(self) -> float:
        """Sampler busy time / wall time enabled, across every enable
        window since the last reset()."""
        with self._lock:
            wall = self._wall_accum
            if self._thread is not None and self._thread.is_alive():
                wall += time.perf_counter() - self._started_at
            busy = self._busy_s
        return busy / wall if wall > 0 else 0.0

    def collapsed(self) -> str:
        """One ``thread;phase;frame;...;frame count`` line per aggregated
        stack — the flamegraph.pl / speedscope collapsed format, with the
        thread name and tracing phase as the two root frames."""
        with self._lock:
            items = sorted(self._table.items())
            dropped = self._dropped_stacks
        lines = []
        for (name, phase, stack), count in items:
            frames_part = ";".join([name, phase or "(no-phase)", *stack])
            lines.append(f"{frames_part} {count}")
        if dropped:
            lines.append(f"(table-overflow);(dropped) {dropped}")
        return "\n".join(lines) + ("\n" if lines else "")

    def top(self, n: int = 20) -> List[Dict[str, Any]]:
        """Top-N frames by self time (leaf-frame sample count)."""
        with self._lock:
            items = list(self._table.items())
            total = self._total_samples
        self_counts: Dict[str, int] = {}
        for (_, _, stack), count in items:
            leaf = stack[-1] if stack else "(unknown)"
            self_counts[leaf] = self_counts.get(leaf, 0) + count
        ranked = sorted(self_counts.items(), key=lambda kv: (-kv[1], kv[0]))[:n]
        return [
            {
                "frame": frame,
                "samples": count,
                "fraction": round(count / total, 4) if total else 0.0,
            }
            for frame, count in ranked
        ]

    def phase_report(self) -> Dict[str, Any]:
        """Per-phase sample counts plus the attributed fraction — the
        "how much of the wall time do the spans explain" number."""
        with self._lock:
            phases = dict(self._phase_samples)
            total = self._total_samples
        attributed = sum(count for phase, count in phases.items() if phase)
        return {
            "total_samples": total,
            "attributed_samples": attributed,
            "attributed_fraction": round(attributed / total, 4) if total else 0.0,
            "phases": {
                phase or "(no-phase)": count
                for phase, count in sorted(phases.items(), key=lambda kv: -kv[1])
            },
        }

    def debug_payload(self, top_n: int = 20) -> Dict[str, Any]:
        """The /debug/profile JSON document."""
        with self._lock:
            stacks = len(self._table)
            dropped = self._dropped_stacks
        return {
            "enabled": self.enabled,
            "interval_seconds": self.interval,
            "threads": sorted(self.threads().values()),
            "stacks": stacks,
            "dropped_stacks": dropped,
            "overhead_fraction": round(self.overhead_fraction(), 6),
            **self.phase_report(),
            "top": self.top(top_n),
        }


# The process-wide profiler (the metrics.REGISTRY / tracing.TRACER analogue).
PROFILER = StackProfiler()
