"""Resource arithmetic over ResourceList maps.

Reference pkg/resource/resource.go:30-146 (Sum/Subtract/Abs; pod request =
Σcontainers ⊔ max(initContainers)) and pkg/gpu/util/resource.go:28-86 (the
ResourceCalculator that injects the synthetic aggregate resource so quotas
can be expressed in one unit — GPU-memory GB there, TPU chips here).
"""
from __future__ import annotations


from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.objects import Pod, ResourceList
from nos_tpu.tpu.known import profile_for_chips
from nos_tpu.tpu.topology import topology_chips


def sum_resources(a: ResourceList, b: ResourceList) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def subtract_resources(a: ResourceList, b: ResourceList) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) - v
    return out


def max_resources(a: ResourceList, b: ResourceList) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = max(out.get(k, 0), v)
    return out


def fits(available: ResourceList, request: ResourceList) -> bool:
    return all(available.get(k, 0) >= v for k, v in request.items())


def nonzero(r: ResourceList) -> ResourceList:
    return {k: v for k, v in r.items() if v != 0}


def compute_pod_request(pod: Pod) -> ResourceList:
    """Effective pod request: Σ(containers) ⊔ max(initContainers).

    Reference pkg/resource/resource.go ComputePodRequest."""
    total: ResourceList = {}
    for c in pod.spec.containers:
        total = sum_resources(total, c.requests)
    for c in pod.spec.init_containers:
        total = max_resources(total, c.requests)
    return total


def tpu_chips_in(request: ResourceList) -> int:
    """Total TPU chips a request amounts to, across plain-chip and sliced
    resources. The aggregate-resource math behind nos.nebuly.com/tpu-chips
    (analogue of reference pkg/gpu/util/resource.go:60-86)."""
    chips = int(request.get(constants.RESOURCE_TPU, 0))
    for name, qty in request.items():
        if constants.is_tpu_slice_resource(name):
            chips += topology_chips(constants.tpu_slice_topology(name)) * int(qty)
    return chips


def tpu_memory_gb_in(
    request: ResourceList, chip_memory_gb: int = constants.DEFAULT_TPU_CHIP_MEMORY_GB
) -> int:
    """Total TPU HBM GB a request amounts to: shared fractions count their
    own size, whole chips and topology slices count `chip_memory_gb` each
    (the gpu-memory aggregate math of reference pkg/gpu/util/resource.go:60-86)."""
    gb = tpu_chips_in(request) * chip_memory_gb
    for name, qty in request.items():
        if constants.is_tpu_shared_resource(name):
            profile = constants.tpu_shared_profile(name)
            gb += constants.shared_profile_gb(profile) * int(qty)
    return gb


def with_aggregate_tpu_chips(
    request: ResourceList,
    chip_memory_gb: int = constants.DEFAULT_TPU_CHIP_MEMORY_GB,
) -> ResourceList:
    """Inject the aggregate quota resources: nos.nebuly.com/tpu-chips (chip
    units) and nos.nebuly.com/tpu-memory (HBM GB), so ElasticQuotas can be
    expressed in either regardless of which extended resource pods ask for.
    `chip_memory_gb` is the per-chip HBM the deployment declares (the
    reference's NvidiaGpuResourceMemoryGB operator knob)."""
    out = dict(request)
    chips = tpu_chips_in(request)
    if chips > 0:
        out[constants.RESOURCE_TPU_CHIPS] = chips
    memory = tpu_memory_gb_in(request, chip_memory_gb)
    if memory > 0:
        out[constants.RESOURCE_TPU_MEMORY] = memory
    return out


def normalize_tpu_request(request: ResourceList, accelerator: str) -> ResourceList:
    """Rewrite a plain ``google.com/tpu: N`` request as one slice request of
    the smallest profile holding N chips. Slice requests pass through.

    Returns the request unchanged when N exceeds every single-board profile
    (multi-host case — handled by gang scheduling, not board carving)."""
    plain = int(request.get(constants.RESOURCE_TPU, 0))
    if plain <= 0:
        return dict(request)
    profile = profile_for_chips(plain, accelerator)
    if profile is None:
        return dict(request)
    out = dict(request)
    del out[constants.RESOURCE_TPU]
    slice_resource = constants.tpu_slice_resource(profile)
    out[slice_resource] = out.get(slice_resource, 0) + 1
    return out
