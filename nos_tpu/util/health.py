"""Health/readiness/metrics HTTP endpoints.

Every reference binary registers healthz/readyz probes and a metrics
endpoint on its controller manager (cmd/operator/operator.go:112-118,
ControllerManagerConfigurationSpec addresses). This serves the same three
endpoints for an in-process component set.
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from nos_tpu.util.metrics import REGISTRY


class HealthServer:
    def __init__(
        self,
        port: int = 8081,
        ready_check: Optional[Callable[[], bool]] = None,
        host: str = "127.0.0.1",
    ) -> None:
        self.port = port
        self.ready_check = ready_check or (lambda: True)
        self.host = host
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Starts serving; returns the bound port (0 picks a free one)."""
        ready_check = self.ready_check

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                if self.path == "/healthz":
                    self._respond(200, "ok")
                elif self.path == "/readyz":
                    if ready_check():
                        self._respond(200, "ok")
                    else:
                        self._respond(503, "not ready")
                elif self.path == "/metrics":
                    self._respond(200, REGISTRY.render(), "text/plain; version=0.0.4")
                else:
                    self._respond(404, "not found")

            def _respond(self, code: int, body: str, ctype: str = "text/plain") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args) -> None:  # silence request logging
                pass

        self._server = ThreadingHTTPServer((self.host, self.port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="health", daemon=True
        )
        self._thread.start()
        return self._server.server_address[1]

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
