"""Health/readiness/metrics HTTP endpoints.

Every reference binary registers healthz/readyz probes and a metrics
endpoint on its controller manager (cmd/operator/operator.go:112-118,
ControllerManagerConfigurationSpec addresses). This serves the same three
endpoints for an in-process component set.

Debug surfaces live in a single registry (:meth:`HealthServer._debug_endpoints`):
registering a handler there is the ONLY step — the bearer gate, the
``/debug`` index, and the index-completeness lint test all derive from
the registry, so an endpoint can never ship ungated or unlisted.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs, urlsplit

from nos_tpu.util.metrics import REGISTRY
from nos_tpu.util.tracing import TRACER


class HealthServer:
    def __init__(
        self,
        port: int = 8081,
        ready_check: Optional[Callable[[], bool]] = None,
        host: str = "127.0.0.1",
        metrics_token: "str | Callable[[], Optional[str]]" = "",
        metrics_loopback_port: Optional[int] = None,
        explain_fn: Optional[Callable[[str], Optional[dict]]] = None,
        record_fn: Optional[Callable[[], list]] = None,
        capacity_fn: Optional[Callable[[], dict]] = None,
        profiler: Optional[Any] = None,
        loops_fn: Optional[Callable[[], dict]] = None,
        slo_fn: Optional[Callable[[], dict]] = None,
        autoscaler_fn: Optional[Callable[[], dict]] = None,
        forecast_fn: Optional[Callable[[bool], dict]] = None,
        timeline_fn: Optional[Callable[[Optional[float]], dict]] = None,
        capacity_stream_fn: Optional[Callable[..., Any]] = None,
        timeline_stream_fn: Optional[Callable[[], Any]] = None,
        debug_page_limit: int = 500,
    ) -> None:
        self.port = port
        self.ready_check = ready_check or (lambda: True)
        self.host = host
        # /debug/explain?pod=ns/name -> the scheduler's latest Diagnosis
        # for the pod (per-node per-plugin rejection ledger) as JSON; None
        # disables the endpoint (components without a scheduler).
        self.explain_fn = explain_fn
        # /debug/record -> the flight recorder's in-memory ring (list of
        # record dicts); None disables the endpoint (recording off).
        self.record_fn = record_fn
        # /debug/capacity -> the CapacityLedger's rollup document (per-node
        # and cluster chip-seconds, idle attribution, fragmentation, gang
        # waits); None disables the endpoint (no ledger wired).
        self.capacity_fn = capacity_fn
        # /debug/profile -> the StackProfiler's collapsed stacks / top-N
        # self-time document, plus ?action=start|stop runtime control;
        # None disables the endpoint.
        self.profiler = profiler
        # /debug/loops -> the LoopHealthRegistry rollup (busy fractions,
        # queue depths, saturation metric families); None disables it.
        self.loops_fn = loops_fn
        # /debug/slo -> the SLOEngine rollup (per-SLO burn rates over the
        # fast/slow windows, compliance, error-budget remaining, recent
        # violations with /debug/traces links); None disables it.
        self.slo_fn = slo_fn
        # /debug/autoscaler -> the ModelServingReconciler rollup (per
        # ModelServing desired/ready replicas, last verdict, cold starts,
        # plus the live signal registry); None disables it.
        self.autoscaler_fn = autoscaler_fn
        # /debug/forecast -> the PlacementForecaster rollup (per-gang
        # ETAs, backfill heatmap, advisor plan, calibration), called with
        # refresh=True when ?refresh=1 forces an on-demand run; None
        # disables the endpoint (no forecaster wired).
        self.forecast_fn = forecast_fn
        # /debug/timeline -> the TimelineStore rollup (windowed per-series
        # rollups + sparkline arrays, watchdog loop registry, detector
        # findings), called with the parsed ?window= seconds (or None for
        # the whole ring); None disables the endpoint (no timeline wired).
        self.timeline_fn = timeline_fn
        # ?format=jsonl generators: /debug/capacity streams one record
        # per node from capacity_stream_fn(pool=...), /debug/timeline
        # streams ring frames from timeline_stream_fn() — both chunked,
        # so no O(cluster) document is ever materialized server-side.
        self.capacity_stream_fn = capacity_stream_fn
        self.timeline_stream_fn = timeline_stream_fn
        # Default page size applied when a paginated debug endpoint gets
        # no explicit ?limit= (0 = unpaginated, the pre-streaming shape).
        # Direct debug_payload() callers are unaffected — the cap lives
        # at the HTTP layer only.
        self.debug_page_limit = debug_page_limit
        # metrics_token non-empty (or a provider callable): /metrics
        # requires `Authorization: Bearer <token>` (the reference protects
        # metrics behind a kube-rbac-proxy TokenReview sidecar,
        # helm-charts/nos/values.yaml:40-55; a shared bearer token is the
        # sidecar-free equivalent — the chart supports BOTH, see
        # values.yaml kubeRbacProxy / metricsAuth). A provider returning
        # None fails CLOSED (401) — a missing/rotating Secret must not
        # silently expose metrics. healthz/readyz stay open: the kubelet
        # probes unauthenticated.
        self.metrics_token = metrics_token
        # Set (kube-rbac-proxy mode): /metrics moves to its own
        # loopback-only listener for the sidecar to front, while
        # healthz/readyz keep serving on (host, port) for kubelet probes —
        # one listener for both would either expose metrics or break the
        # probes.
        self.metrics_loopback_port = metrics_loopback_port
        self._servers: list = []
        self._threads: list = []

    # ----------------------------------------------------- debug registry

    def _debug_endpoints(self) -> Dict[str, Dict[str, Any]]:
        """The debug surface registry: path -> {"describe", "handle"}.
        Every entry is bearer-gated by the dispatcher (same credential as
        /metrics — all of them carry pod/node/namespace identifiers) and
        listed in the auto-built /debug index. Conditional entries appear
        only when their callback is wired, so the index never lists a 404.
        """
        endpoints: Dict[str, Dict[str, Any]] = {}

        def register(
            path: str, describe: str, handle: Callable[[Any, Any], None]
        ) -> None:
            endpoints[path] = {"describe": describe, "handle": handle}

        register(
            "/debug/traces",
            "per-trace summaries newest-first with retention accounting; "
            "?id=<trace_id> for the full Chrome trace-event timeline; "
            "?limit=/?cursor= paginate, ?format=jsonl streams one summary "
            "per line",
            self._serve_traces,
        )
        register(
            "/debug/vars",
            "the MetricsRegistry snapshot as flat JSON",
            self._serve_vars,
        )
        if self.explain_fn is not None:
            register(
                "/debug/explain",
                "?pod=<namespace>/<name> — the scheduler's latest per-node "
                "per-plugin rejection Diagnosis for the pod",
                self._serve_explain,
            )
        if self.record_fn is not None:
            register(
                "/debug/record",
                "the flight recorder's decision ring; ?format=jsonl for "
                "`python -m nos_tpu replay` input",
                self._serve_record,
            )
        if self.capacity_fn is not None:
            register(
                "/debug/capacity",
                "the capacity ledger: chip-seconds accounting, idle "
                "attribution, fragmentation, gang waits, quota posture; "
                "?pool= filters, ?limit=/?cursor= paginate the node table, "
                "?format=jsonl streams one record per node",
                self._serve_capacity,
            )
        if self.profiler is not None:
            register(
                "/debug/profile",
                "the control-plane sampling profiler: JSON top-N self-time "
                "and phase attribution; ?format=collapsed for flamegraph "
                "input; ?action=start|stop for runtime control",
                self._serve_profile,
            )
        if self.loops_fn is not None:
            register(
                "/debug/loops",
                "loop-health rollup: per-loop busy fractions, watch queue "
                "depths, drain lag and phase-duration metric families",
                self._serve_loops,
            )
        if self.slo_fn is not None:
            register(
                "/debug/slo",
                "serving SLO rollup: per-SLO fast/slow-window burn rates, "
                "compliance, error-budget remaining, recent violations "
                "linked into /debug/traces",
                self._serve_slo,
            )
        if self.autoscaler_fn is not None:
            register(
                "/debug/autoscaler",
                "model autoscaler rollup: per-ModelServing desired/ready "
                "replicas, last verdict, cold starts, and the burn/queue "
                "signal registry",
                self._serve_autoscaler,
            )
        if self.forecast_fn is not None:
            register(
                "/debug/forecast",
                "placement forecast: per-gang earliest-feasible-start ETAs "
                "with blocking sets linked into /debug/explain, the "
                "backfill-safety heatmap, the defrag advisor's plan, and "
                "ETA calibration; ?refresh=1 forces an on-demand run",
                self._serve_forecast,
            )
        if self.timeline_fn is not None:
            register(
                "/debug/timeline",
                "the longitudinal health timeline: windowed per-series "
                "rollups and sparkline arrays over the sampled ring, the "
                "wedge-watchdog loop registry, and leak/stall/regression "
                "detector findings; ?window=<seconds> bounds the rollup "
                "window, ?limit=/?cursor= paginate the per-series tables, "
                "?format=jsonl streams the delta-encoded ring frames",
                self._serve_timeline,
            )
        return endpoints

    # Endpoint handlers: called with the live request handler (for
    # _respond and headers) and the split URL, after the bearer gate.

    def _page_params(self, req, url) -> Optional[dict]:
        """Parsed ?pool=/?limit=/?cursor=/?format= with the server's
        default page size; responds 400 and returns None on a bad limit."""
        from nos_tpu.obsplane.streaming import page_params

        query = {k: v[0] for k, v in parse_qs(url.query).items()}
        try:
            return page_params(query, default_limit=self.debug_page_limit)
        except ValueError:
            req._respond(400, "limit must be a non-negative integer")
            return None

    def _serve_traces(self, req, url) -> None:
        wanted = parse_qs(url.query).get("id", [None])[0]
        if wanted:
            trace = TRACER.store.get(wanted)
            if trace is None:
                req._respond(404, "unknown trace id")
                return
            req._respond(200, json.dumps(trace.to_chrome(), indent=2), "application/json")
            return
        page = self._page_params(req, url)
        if page is None:
            return
        summaries, next_cursor = TRACER.store.summaries_page(
            limit=page["limit"], cursor=page["cursor"]
        )
        if page["jsonl"]:
            from nos_tpu.obsplane.streaming import jsonl_lines

            req._respond_stream(200, jsonl_lines(summaries))
            return
        body = json.dumps(
            {
                "traces": summaries,
                "retention": TRACER.store.retention_stats(),
                "page": {"limit": page["limit"], "next_cursor": next_cursor},
            },
            indent=2,
        )
        req._respond(200, body, "application/json")

    def _serve_vars(self, req, url) -> None:
        body = json.dumps(REGISTRY.snapshot(), indent=2, sort_keys=True)
        req._respond(200, body, "application/json")

    def _serve_explain(self, req, url) -> None:
        pod_key = parse_qs(url.query).get("pod", [None])[0]
        if not pod_key:
            req._respond(400, "missing ?pod=namespace/name")
            return
        diagnosis = self.explain_fn(pod_key)
        if diagnosis is None:
            req._respond(404, "no diagnosis recorded for pod")
            return
        req._respond(200, json.dumps(diagnosis, indent=2), "application/json")

    def _serve_record(self, req, url) -> None:
        records = self.record_fn()
        fmt = parse_qs(url.query).get("format", ["json"])[0]
        if fmt == "jsonl":
            # Directly consumable by `python -m nos_tpu replay`.
            body = "".join(json.dumps(r) + "\n" for r in records)
            req._respond(200, body, "application/x-ndjson")
        else:
            req._respond(200, json.dumps(records, indent=2), "application/json")

    def _serve_capacity(self, req, url) -> None:
        page = self._page_params(req, url)
        if page is None:
            return
        if page["jsonl"] and self.capacity_stream_fn is not None:
            from nos_tpu.obsplane.streaming import jsonl_lines

            req._respond_stream(
                200, jsonl_lines(self.capacity_stream_fn(pool=page["pool"]))
            )
            return
        try:
            payload = self.capacity_fn(
                pool=page["pool"], limit=page["limit"], cursor=page["cursor"]
            )
        except TypeError:
            # A legacy zero-arg capacity_fn (tests, minimal wiring): serve
            # the unpaginated document it returns.
            payload = self.capacity_fn()
        req._respond(200, json.dumps(payload, indent=2), "application/json")

    def _serve_profile(self, req, url) -> None:
        query = parse_qs(url.query)
        action = query.get("action", [None])[0]
        if action == "start":
            started = self.profiler.start()
            req._respond(
                200,
                json.dumps({"enabled": True, "started": started}),
                "application/json",
            )
            return
        if action == "stop":
            stopped = self.profiler.stop()
            req._respond(
                200,
                json.dumps({"enabled": False, "stopped": stopped}),
                "application/json",
            )
            return
        if action is not None:
            req._respond(400, "action must be start or stop")
            return
        fmt = query.get("format", ["json"])[0]
        if fmt == "collapsed":
            # flamegraph.pl / speedscope input, one aggregated stack per
            # line.
            req._respond(200, self.profiler.collapsed())
        else:
            req._respond(
                200,
                json.dumps(self.profiler.debug_payload(), indent=2),
                "application/json",
            )

    def _serve_loops(self, req, url) -> None:
        req._respond(
            200, json.dumps(self.loops_fn(), indent=2), "application/json"
        )

    def _serve_slo(self, req, url) -> None:
        req._respond(
            200, json.dumps(self.slo_fn(), indent=2), "application/json"
        )

    def _serve_autoscaler(self, req, url) -> None:
        req._respond(
            200, json.dumps(self.autoscaler_fn(), indent=2), "application/json"
        )

    def _serve_forecast(self, req, url) -> None:
        refresh = parse_qs(url.query).get("refresh", ["0"])[0] in ("1", "true")
        req._respond(
            200,
            json.dumps(self.forecast_fn(refresh), indent=2),
            "application/json",
        )

    def _serve_timeline(self, req, url) -> None:
        page = self._page_params(req, url)
        if page is None:
            return
        if page["jsonl"] and self.timeline_stream_fn is not None:
            from nos_tpu.obsplane.streaming import jsonl_lines

            req._respond_stream(200, jsonl_lines(self.timeline_stream_fn()))
            return
        raw = parse_qs(url.query).get("window", [None])[0]
        window: Optional[float] = None
        if raw is not None:
            try:
                window = float(raw)
            except ValueError:
                req._respond(400, "window must be a number of seconds")
                return
        try:
            payload = self.timeline_fn(
                window, limit=page["limit"], cursor=page["cursor"]
            )
        except TypeError:
            payload = self.timeline_fn(window)
        req._respond(
            200,
            json.dumps(payload, indent=2, sort_keys=True),
            "application/json",
        )

    # ------------------------------------------------------------ serving

    def _make_handler(self, serve_health: bool, serve_metrics: bool):
        ready_check = self.ready_check
        metrics_token = self.metrics_token
        endpoints = self._debug_endpoints()
        # The /debug/ index IS the registry: every debug surface this
        # listener serves, with a one-liner, derived from the same table
        # the dispatcher routes (and gates) with.
        debug_index = {
            path: entry["describe"] for path, entry in endpoints.items()
        }

        auth_enabled = bool(metrics_token)  # provider callable or token set

        def current_token() -> Optional[str]:
            if callable(metrics_token):
                return metrics_token()
            return metrics_token

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 so chunked transfer encoding (the ?format=jsonl
            # streaming paths) is legal; _respond always sets
            # Content-Length so fixed responses stay keep-alive-safe.
            protocol_version = "HTTP/1.1"
            # Idle keep-alive connections must not pin handler threads
            # past shutdown: the socket timeout makes handle_one_request
            # drop a quiet persistent connection instead of blocking in
            # readline() forever.
            timeout = 5.0

            def _authorized(self) -> bool:
                if not auth_enabled:
                    return True
                token = current_token()
                # Fail CLOSED on a missing or empty token (file vanished
                # or emptied mid-rotation) — never serve unauthenticated
                # because the credential source degraded.
                return bool(token) and (
                    self.headers.get("Authorization", "") == f"Bearer {token}"
                )

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                url = urlsplit(self.path)
                path = url.path
                if path == "/healthz" and serve_health:
                    self._respond(200, "ok")
                elif path == "/readyz" and serve_health:
                    if ready_check():
                        self._respond(200, "ok")
                    else:
                        self._respond(503, "not ready")
                elif path == "/metrics" and serve_metrics:
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    self._respond(200, REGISTRY.render(), "text/plain; version=0.0.4")
                elif path in endpoints and serve_metrics:
                    # One gate for every registered debug surface: all of
                    # them carry identifiers as sensitive as the series.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    endpoints[path]["handle"](self, url)
                elif path in ("/debug", "/debug/") and serve_metrics:
                    # Bearer-gated like every endpoint it links to — the
                    # index itself reveals which subsystems are wired.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    body = json.dumps({"endpoints": debug_index}, indent=2)
                    self._respond(200, body, "application/json")
                else:
                    self._respond(404, "not found")

            def _respond(self, code: int, body: str, ctype: str = "text/plain") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _respond_stream(
                self,
                code: int,
                chunks,
                ctype: str = "application/x-ndjson",
            ) -> None:
                """Chunked transfer encoding over an iterable of bytes —
                the response is produced incrementally, never buffered
                whole, so streaming debug endpoints stay O(1) in cluster
                size server-side."""
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    for chunk in chunks:
                        if not chunk:
                            continue
                        self.wfile.write(f"{len(chunk):X}\r\n".encode())
                        self.wfile.write(chunk)
                        self.wfile.write(b"\r\n")
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    # Client went away mid-stream; nothing to salvage.
                    self.close_connection = True

            def log_message(self, *args) -> None:  # silence request logging
                pass

        return Handler

    def start(self) -> int:
        """Starts serving; returns the bound health port (0 picks a free
        one)."""
        split = self.metrics_loopback_port is not None
        main = ThreadingHTTPServer(
            (self.host, self.port),
            self._make_handler(serve_health=True, serve_metrics=not split),
        )
        self._servers = [main]
        if split:
            self._servers.append(
                ThreadingHTTPServer(
                    ("127.0.0.1", self.metrics_loopback_port),
                    self._make_handler(serve_health=False, serve_metrics=True),
                )
            )
        self._threads = []
        for i, server in enumerate(self._servers):
            thread = threading.Thread(
                target=server.serve_forever, name=f"health-{i}", daemon=True
            )
            thread.start()
            self._threads.append(thread)
        return main.server_address[1]

    def stop(self) -> None:
        for server in self._servers:
            server.shutdown()
            server.server_close()
        for thread in self._threads:
            thread.join(timeout=2.0)
