"""Health/readiness/metrics HTTP endpoints.

Every reference binary registers healthz/readyz probes and a metrics
endpoint on its controller manager (cmd/operator/operator.go:112-118,
ControllerManagerConfigurationSpec addresses). This serves the same three
endpoints for an in-process component set.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlsplit

from nos_tpu.util.metrics import REGISTRY
from nos_tpu.util.tracing import TRACER


class HealthServer:
    def __init__(
        self,
        port: int = 8081,
        ready_check: Optional[Callable[[], bool]] = None,
        host: str = "127.0.0.1",
        metrics_token: "str | Callable[[], Optional[str]]" = "",
        metrics_loopback_port: Optional[int] = None,
        explain_fn: Optional[Callable[[str], Optional[dict]]] = None,
        record_fn: Optional[Callable[[], list]] = None,
        capacity_fn: Optional[Callable[[], dict]] = None,
        profiler: Optional[Any] = None,
        loops_fn: Optional[Callable[[], dict]] = None,
        slo_fn: Optional[Callable[[], dict]] = None,
        autoscaler_fn: Optional[Callable[[], dict]] = None,
    ) -> None:
        self.port = port
        self.ready_check = ready_check or (lambda: True)
        self.host = host
        # /debug/explain?pod=ns/name -> the scheduler's latest Diagnosis
        # for the pod (per-node per-plugin rejection ledger) as JSON; None
        # disables the endpoint (components without a scheduler).
        self.explain_fn = explain_fn
        # /debug/record -> the flight recorder's in-memory ring (list of
        # record dicts); None disables the endpoint (recording off).
        self.record_fn = record_fn
        # /debug/capacity -> the CapacityLedger's rollup document (per-node
        # and cluster chip-seconds, idle attribution, fragmentation, gang
        # waits); None disables the endpoint (no ledger wired).
        self.capacity_fn = capacity_fn
        # /debug/profile -> the StackProfiler's collapsed stacks / top-N
        # self-time document, plus ?action=start|stop runtime control;
        # None disables the endpoint.
        self.profiler = profiler
        # /debug/loops -> the LoopHealthRegistry rollup (busy fractions,
        # queue depths, saturation metric families); None disables it.
        self.loops_fn = loops_fn
        # /debug/slo -> the SLOEngine rollup (per-SLO burn rates over the
        # fast/slow windows, compliance, error-budget remaining, recent
        # violations with /debug/traces links); None disables it.
        self.slo_fn = slo_fn
        # /debug/autoscaler -> the ModelServingReconciler rollup (per
        # ModelServing desired/ready replicas, last verdict, cold starts,
        # plus the live signal registry); None disables it.
        self.autoscaler_fn = autoscaler_fn
        # metrics_token non-empty (or a provider callable): /metrics
        # requires `Authorization: Bearer <token>` (the reference protects
        # metrics behind a kube-rbac-proxy TokenReview sidecar,
        # helm-charts/nos/values.yaml:40-55; a shared bearer token is the
        # sidecar-free equivalent — the chart supports BOTH, see
        # values.yaml kubeRbacProxy / metricsAuth). A provider returning
        # None fails CLOSED (401) — a missing/rotating Secret must not
        # silently expose metrics. healthz/readyz stay open: the kubelet
        # probes unauthenticated.
        self.metrics_token = metrics_token
        # Set (kube-rbac-proxy mode): /metrics moves to its own
        # loopback-only listener for the sidecar to front, while
        # healthz/readyz keep serving on (host, port) for kubelet probes —
        # one listener for both would either expose metrics or break the
        # probes.
        self.metrics_loopback_port = metrics_loopback_port
        self._servers: list = []
        self._threads: list = []

    def _make_handler(self, serve_health: bool, serve_metrics: bool):
        ready_check = self.ready_check
        metrics_token = self.metrics_token
        explain_fn = self.explain_fn
        record_fn = self.record_fn
        capacity_fn = self.capacity_fn
        profiler = self.profiler
        loops_fn = self.loops_fn
        slo_fn = self.slo_fn
        autoscaler_fn = self.autoscaler_fn

        # The /debug/ index: every debug surface this listener actually
        # serves, with a one-liner. Conditional entries appear only when
        # their callback is wired, so the index never lists a 404.
        debug_index = {
            "/debug/traces": "per-trace summaries; ?id=<trace_id> for the "
            "full Chrome trace-event timeline",
            "/debug/vars": "the MetricsRegistry snapshot as flat JSON",
        }
        if explain_fn is not None:
            debug_index["/debug/explain"] = (
                "?pod=<namespace>/<name> — the scheduler's latest per-node "
                "per-plugin rejection Diagnosis for the pod"
            )
        if record_fn is not None:
            debug_index["/debug/record"] = (
                "the flight recorder's decision ring; ?format=jsonl for "
                "`python -m nos_tpu replay` input"
            )
        if capacity_fn is not None:
            debug_index["/debug/capacity"] = (
                "the capacity ledger: chip-seconds accounting, idle "
                "attribution, fragmentation, gang waits, quota posture"
            )
        if profiler is not None:
            debug_index["/debug/profile"] = (
                "the control-plane sampling profiler: JSON top-N self-time "
                "and phase attribution; ?format=collapsed for flamegraph "
                "input; ?action=start|stop for runtime control"
            )
        if loops_fn is not None:
            debug_index["/debug/loops"] = (
                "loop-health rollup: per-loop busy fractions, watch queue "
                "depths, drain lag and phase-duration metric families"
            )
        if slo_fn is not None:
            debug_index["/debug/slo"] = (
                "serving SLO rollup: per-SLO fast/slow-window burn rates, "
                "compliance, error-budget remaining, recent violations "
                "linked into /debug/traces"
            )
        if autoscaler_fn is not None:
            debug_index["/debug/autoscaler"] = (
                "model autoscaler rollup: per-ModelServing desired/ready "
                "replicas, last verdict, cold starts, and the burn/queue "
                "signal registry"
            )

        auth_enabled = bool(metrics_token)  # provider callable or token set

        def current_token() -> Optional[str]:
            if callable(metrics_token):
                return metrics_token()
            return metrics_token

        class Handler(BaseHTTPRequestHandler):
            def _authorized(self) -> bool:
                if not auth_enabled:
                    return True
                token = current_token()
                # Fail CLOSED on a missing or empty token (file vanished
                # or emptied mid-rotation) — never serve unauthenticated
                # because the credential source degraded.
                return bool(token) and (
                    self.headers.get("Authorization", "") == f"Bearer {token}"
                )

            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                url = urlsplit(self.path)
                path = url.path
                if path == "/healthz" and serve_health:
                    self._respond(200, "ok")
                elif path == "/readyz" and serve_health:
                    if ready_check():
                        self._respond(200, "ok")
                    else:
                        self._respond(503, "not ready")
                elif path == "/metrics" and serve_metrics:
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    self._respond(200, REGISTRY.render(), "text/plain; version=0.0.4")
                elif path == "/debug/traces" and serve_metrics:
                    # Same credential as /metrics: trace attributes carry
                    # pod names and namespaces, as sensitive as the series.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    wanted = parse_qs(url.query).get("id", [None])[0]
                    if wanted:
                        trace = TRACER.store.get(wanted)
                        if trace is None:
                            self._respond(404, "unknown trace id")
                            return
                        body = json.dumps(trace.to_chrome(), indent=2)
                    else:
                        body = json.dumps(TRACER.store.summaries(), indent=2)
                    self._respond(200, body, "application/json")
                elif (
                    path == "/debug/explain"
                    and serve_metrics
                    and explain_fn is not None
                ):
                    # Same credential as /metrics: the diagnosis carries
                    # pod names, namespaces, and rejection details.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    pod_key = parse_qs(url.query).get("pod", [None])[0]
                    if not pod_key:
                        self._respond(400, "missing ?pod=namespace/name")
                        return
                    diagnosis = explain_fn(pod_key)
                    if diagnosis is None:
                        self._respond(404, "no diagnosis recorded for pod")
                        return
                    self._respond(
                        200, json.dumps(diagnosis, indent=2), "application/json"
                    )
                elif (
                    path == "/debug/record"
                    and serve_metrics
                    and record_fn is not None
                ):
                    # Same credential as /metrics: decision records carry
                    # pod names, namespaces, and full object deltas.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    records = record_fn()
                    fmt = parse_qs(url.query).get("format", ["json"])[0]
                    if fmt == "jsonl":
                        # Directly consumable by `python -m nos_tpu replay`.
                        body = "".join(json.dumps(r) + "\n" for r in records)
                        self._respond(200, body, "application/x-ndjson")
                    else:
                        self._respond(
                            200, json.dumps(records, indent=2), "application/json"
                        )
                elif path == "/debug/vars" and serve_metrics:
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    body = json.dumps(REGISTRY.snapshot(), indent=2, sort_keys=True)
                    self._respond(200, body, "application/json")
                elif (
                    path == "/debug/capacity"
                    and serve_metrics
                    and capacity_fn is not None
                ):
                    # Same credential as /metrics: the rollup carries node,
                    # pod, and namespace names.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    body = json.dumps(capacity_fn(), indent=2)
                    self._respond(200, body, "application/json")
                elif (
                    path == "/debug/profile"
                    and serve_metrics
                    and profiler is not None
                ):
                    # Same credential as /metrics: stack frames reveal
                    # code paths and the phase labels carry span names.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    query = parse_qs(url.query)
                    action = query.get("action", [None])[0]
                    if action == "start":
                        started = profiler.start()
                        self._respond(
                            200,
                            json.dumps(
                                {"enabled": True, "started": started}
                            ),
                            "application/json",
                        )
                        return
                    if action == "stop":
                        stopped = profiler.stop()
                        self._respond(
                            200,
                            json.dumps(
                                {"enabled": False, "stopped": stopped}
                            ),
                            "application/json",
                        )
                        return
                    if action is not None:
                        self._respond(400, "action must be start or stop")
                        return
                    fmt = query.get("format", ["json"])[0]
                    if fmt == "collapsed":
                        # flamegraph.pl / speedscope input, one aggregated
                        # stack per line.
                        self._respond(200, profiler.collapsed())
                    else:
                        self._respond(
                            200,
                            json.dumps(profiler.debug_payload(), indent=2),
                            "application/json",
                        )
                elif (
                    path == "/debug/loops"
                    and serve_metrics
                    and loops_fn is not None
                ):
                    # Same credential as /metrics: loop names and watcher
                    # labels identify the deployment's topology.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    self._respond(
                        200, json.dumps(loops_fn(), indent=2), "application/json"
                    )
                elif (
                    path == "/debug/slo"
                    and serve_metrics
                    and slo_fn is not None
                ):
                    # Same credential as /metrics: violation entries carry
                    # request/model identifiers and trace links.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    self._respond(
                        200, json.dumps(slo_fn(), indent=2), "application/json"
                    )
                elif (
                    path == "/debug/autoscaler"
                    and serve_metrics
                    and autoscaler_fn is not None
                ):
                    # Same credential as /metrics: the rollup names models
                    # and ModelServing objects.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    self._respond(
                        200,
                        json.dumps(autoscaler_fn(), indent=2),
                        "application/json",
                    )
                elif path in ("/debug", "/debug/") and serve_metrics:
                    # Bearer-gated like every endpoint it links to — the
                    # index itself reveals which subsystems are wired.
                    if not self._authorized():
                        self._respond(401, "unauthorized")
                        return
                    body = json.dumps({"endpoints": debug_index}, indent=2)
                    self._respond(200, body, "application/json")
                else:
                    self._respond(404, "not found")

            def _respond(self, code: int, body: str, ctype: str = "text/plain") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args) -> None:  # silence request logging
                pass

        return Handler

    def start(self) -> int:
        """Starts serving; returns the bound health port (0 picks a free
        one)."""
        split = self.metrics_loopback_port is not None
        main = ThreadingHTTPServer(
            (self.host, self.port),
            self._make_handler(serve_health=True, serve_metrics=not split),
        )
        self._servers = [main]
        if split:
            self._servers.append(
                ThreadingHTTPServer(
                    ("127.0.0.1", self.metrics_loopback_port),
                    self._make_handler(serve_health=False, serve_metrics=True),
                )
            )
        self._threads = []
        for i, server in enumerate(self._servers):
            thread = threading.Thread(
                target=server.serve_forever, name=f"health-{i}", daemon=True
            )
            thread.start()
            self._threads.append(thread)
        return main.server_address[1]

    def stop(self) -> None:
        for server in self._servers:
            server.shutdown()
            server.server_close()
        for thread in self._threads:
            thread.join(timeout=2.0)
