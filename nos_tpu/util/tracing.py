"""Request-scoped tracing for the pod-lifecycle pipeline.

The north-star metric — time from pending Pod to bound slice — was a single
histogram with no decomposition: a slow cycle could not be attributed to
quota checks, planner fork trials, actuation, or device-plugin reconfig.
This module adds Dapper-style spans over the in-process control plane:

- ``Span``: trace/span/parent ids, attributes, events, wall+perf clocks.
- Propagation rides ``contextvars``: a component opens a child span with
  ``TRACER.span(...)`` and the active span is picked up implicitly, no
  argument plumbing through the scheduler framework or the planner.
  Threads don't inherit contextvars, so cross-thread handoffs use
  ``TRACER.attach(span)`` (explicit re-parenting in the worker) or a
  journey/link lookup (below).
- The pending-Pod *journey* spans several controller threads connected by
  store events, not call stacks, so correlation is keyed: a journey root
  span is registered under ``("pod", namespaced_name)`` by whichever
  controller observes the pod first, later stages look it up
  (``journey``/``journey_root``) and parent onto it, and the scheduler ends
  it at bind. Asynchronous actuation handoffs (spec annotation → tpuagent)
  are correlated through ``link``/``linked`` with an explicit key carried
  by the plan id.
- Completed traces land in a bounded in-memory ``TraceStore`` ring,
  exportable as Chrome trace-event JSON (loadable in Perfetto / Chrome
  ``about:tracing``) and as a compact per-stage summary.

Everything is bounded: spans per trace, events per span, live journeys,
links, and stored traces all have caps, so a long-running scheduler can
leave tracing on. With ``TRACER.enabled = False`` every entry point
short-circuits to a shared no-op span (the overhead guard in
``tests/partitioning/test_planner_perf.py`` keeps that path honest).
"""
from __future__ import annotations

import contextlib
import itertools
import json
import logging
import threading
import time
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

_ids = itertools.count(1)


def _new_id(prefix: str) -> str:
    return f"{prefix}{next(_ids):x}"


_current_span: ContextVar[Optional["Span"]] = ContextVar(
    "nos_tpu_current_span", default=None
)
# Thread id -> innermost active span NAME, maintained on span()/attach()
# enter/exit. The sampling profiler (util/profiling.py) reads this from its
# own sampler thread to attribute wall-clock samples to tracing phases.
# A plain dict is safe here: each key is written only by the thread it
# names, the sampler only reads, and the GIL makes single dict operations
# atomic — so the span hot path pays two dict ops, no lock.
_thread_phases: Dict[int, str] = {}


def current_phase(thread_id: int) -> str:
    """Name of the thread's innermost active span ('' outside any span or
    while tracing is disabled)."""
    return _thread_phases.get(thread_id, "")
# Planner simulation runs the scheduler framework thousands of times per
# plan(); per-plugin spans there are volume without information. The
# planner raises this flag around its trials; framework plugin spans check
# it (their own spans — trial spans — stay on).
_plugins_suppressed: ContextVar[bool] = ContextVar(
    "nos_tpu_plugin_spans_suppressed", default=False
)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[Tuple[float, str, Dict[str, Any]]] = field(default_factory=list)
    start_wall: float = 0.0
    start_perf: float = 0.0
    duration_s: Optional[float] = None
    thread: str = ""
    status: str = "ok"

    MAX_EVENTS = 128

    @property
    def ended(self) -> bool:
        return self.duration_s is not None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def set_attributes(self, **attributes: Any) -> None:
        self.attributes.update(attributes)

    def add_event(self, name: str, **attributes: Any) -> None:
        if len(self.events) < self.MAX_EVENTS:
            self.events.append((time.time(), name, attributes))

    def to_chrome_events(self) -> List[Dict[str, Any]]:
        """Chrome trace-event 'X' (complete) record plus one 'i' (instant)
        record per span event — the JSON shape Perfetto loads directly."""
        args = dict(self.attributes)
        args["span_id"] = self.span_id
        if self.parent_id:
            args["parent_id"] = self.parent_id
        args["status"] = self.status
        out = [
            {
                "name": self.name,
                "cat": "nos_tpu",
                "ph": "X",
                "ts": round(self.start_wall * 1e6, 1),
                "dur": round((self.duration_s or 0.0) * 1e6, 1),
                "pid": 1,
                "tid": self.thread or "main",
                "args": args,
            }
        ]
        for when, name, attributes in self.events:
            out.append(
                {
                    "name": name,
                    "cat": "nos_tpu.event",
                    "ph": "i",
                    "s": "t",
                    "ts": round(when * 1e6, 1),
                    "pid": 1,
                    "tid": self.thread or "main",
                    "args": dict(attributes),
                }
            )
        return out


class _NoopSpan(Span):
    """Shared sink for disabled tracing: every mutator is a no-op, so hot
    paths can call set_attribute/add_event unconditionally."""

    def __init__(self) -> None:
        super().__init__(name="noop", trace_id="", span_id="")

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def set_attributes(self, **attributes: Any) -> None:
        pass

    def add_event(self, name: str, **attributes: Any) -> None:
        pass


NOOP_SPAN = _NoopSpan()


@dataclass
class Trace:
    """A finalized trace: the root plus every span that ended under it."""

    trace_id: str
    spans: List[Span]
    dropped_spans: int = 0

    @property
    def root(self) -> Optional[Span]:
        for span in self.spans:
            if span.parent_id is None:
                return span
        return self.spans[0] if self.spans else None

    def summary(self) -> Dict[str, Any]:
        """Compact stage breakdown: direct children of the root aggregated
        by span name — the "where did the 2.3 s go" answer."""
        root = self.root
        stages: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        if root is not None:
            for span in self.spans:
                if span.parent_id == root.span_id:
                    stages[span.name] = stages.get(span.name, 0.0) + (
                        span.duration_s or 0.0
                    )
                    counts[span.name] = counts.get(span.name, 0) + 1
        return {
            "trace_id": self.trace_id,
            "root": root.name if root else "",
            "attributes": dict(root.attributes) if root else {},
            "status": root.status if root else "",
            "start": root.start_wall if root else 0.0,
            "duration_s": round(root.duration_s or 0.0, 6) if root else 0.0,
            "spans": len(self.spans),
            "dropped_spans": self.dropped_spans,
            "stages": {
                name: {"total_s": round(total, 6), "count": counts[name]}
                for name, total in sorted(stages.items())
            },
        }

    def to_chrome(self) -> Dict[str, Any]:
        events: List[Dict[str, Any]] = []
        for span in self.spans:
            events.extend(span.to_chrome_events())
        return {
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": self.trace_id},
            "traceEvents": events,
        }


@dataclass
class RetentionPolicy:
    """Tail-kept trace retention: what counts as interesting, how many
    interesting traces are pinned, and how boring traffic is sampled.
    The defaults reproduce the pre-policy store exactly (every trace
    kept in one newest-wins ring) except that interesting traces move
    to the pinned reservoir — where boring bursts cannot evict them."""

    # Pinned reservoir capacity for error/unschedulable/slow traces;
    # 0 disables pinning (every trace competes in the main ring).
    tail_capacity: int = 64
    # Keep 1 of every N boring traces (deterministic head sampling by
    # arrival count); 1 keeps all. Sampled-out traces still count in
    # ``retention_stats`` so kept traces carry weight N, keeping
    # rate/latency estimates over the ring unbiased.
    boring_sample_n: int = 1
    # Root-span name -> seconds; a trace whose root ran longer is
    # classified "slow" and pinned. Unlisted kinds are never slow.
    slow_thresholds: Dict[str, float] = field(default_factory=dict)


def classify_trace(trace: Trace, policy: RetentionPolicy) -> str:
    """'error' | 'unschedulable' | 'slow' | 'boring' — first match wins.

    Unschedulable detection keys off the ``diagnosis`` attribute the
    scheduler's ``_fail_cycle`` stamps on the journey root; error beats
    it so a failed cycle that also raised classifies by the raise.
    """
    for span in trace.spans:
        if span.status == "error":
            return "error"
    root = trace.root
    if root is not None:
        if "diagnosis" in root.attributes:
            return "unschedulable"
        threshold = policy.slow_thresholds.get(root.name)
        if threshold is not None and (root.duration_s or 0.0) > threshold:
            return "slow"
    return "boring"


class TraceStore:
    """Bounded ring of completed traces, newest kept, with id lookup —
    plus a pinned tail reservoir interesting traces retire to, which a
    burst of boring journeys cannot evict (the 100k-node failure mode:
    one failed gang trace vs. thousands of healthy binds per window)."""

    def __init__(
        self, capacity: int = 256, retention: Optional[RetentionPolicy] = None
    ) -> None:
        from collections import OrderedDict

        self.capacity = max(1, capacity)
        self._traces: "OrderedDict[str, Trace]" = OrderedDict()
        self._interesting: "OrderedDict[str, Trace]" = OrderedDict()
        self._lock = threading.Lock()
        self._retention = retention or RetentionPolicy()
        # trace_id -> (arrival seq, verdict): seq orders the merged
        # listing newest-first across both rings and feeds the paging
        # cursor; verdict rides into summaries.
        self._meta: Dict[str, Tuple[int, str]] = {}
        self._seq = 0
        self._seen: Dict[str, int] = {}
        self._kept: Dict[str, int] = {}
        self._sampled_out = 0

    def set_retention(self, policy: Optional[RetentionPolicy]) -> RetentionPolicy:
        """Swap the retention policy; returns the previous one (callers
        applying non-default policy revert it, the registry is shared)."""
        policy = policy or RetentionPolicy()
        with self._lock:
            prev, self._retention = self._retention, policy
            while len(self._interesting) > max(0, policy.tail_capacity):
                evicted, _ = self._interesting.popitem(last=False)
                self._meta.pop(evicted, None)
        return prev

    def add(self, trace: Trace) -> None:
        verdict = classify_trace(trace, self._retention)
        pinned = False
        with self._lock:
            policy = self._retention
            self._seen[verdict] = self._seen.get(verdict, 0) + 1
            if verdict != "boring" and policy.tail_capacity > 0:
                pinned = True
                self._interesting[trace.trace_id] = trace
                self._interesting.move_to_end(trace.trace_id)
                while len(self._interesting) > policy.tail_capacity:
                    evicted, _ = self._interesting.popitem(last=False)
                    self._meta.pop(evicted, None)
            else:
                if verdict == "boring" and policy.boring_sample_n > 1:
                    # Deterministic head sampling by arrival index: the
                    # 1st, N+1th, ... boring traces are kept, the rest
                    # only weigh the counters.
                    if (self._seen[verdict] - 1) % policy.boring_sample_n:
                        self._sampled_out += 1
                        return
                self._traces[trace.trace_id] = trace
                self._traces.move_to_end(trace.trace_id)
                while len(self._traces) > self.capacity:
                    evicted, _ = self._traces.popitem(last=False)
                    self._meta.pop(evicted, None)
            self._seq += 1
            self._meta[trace.trace_id] = (self._seq, verdict)
            self._kept[verdict] = self._kept.get(verdict, 0) + 1
        if pinned:
            from nos_tpu.util import metrics as _metrics

            _metrics.TRACE_RETAINED.labels(verdict=verdict).inc()

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            return self._traces.get(trace_id) or self._interesting.get(trace_id)

    def list(self) -> List[Trace]:
        """Newest first across both rings (merged by arrival order)."""
        with self._lock:
            traces = list(self._traces.values()) + list(self._interesting.values())
            return sorted(
                traces,
                key=lambda t: self._meta.get(t.trace_id, (0, ""))[0],
                reverse=True,
            )

    def summaries(self) -> List[Dict[str, Any]]:
        return [self._summarize(t) for t in self.list()]

    def _summarize(self, trace: Trace) -> Dict[str, Any]:
        seq, verdict = self._meta.get(trace.trace_id, (0, ""))
        out = trace.summary()
        out["seq"] = seq
        out["verdict"] = verdict
        return out

    def summaries_page(
        self, limit: int = 0, cursor: str = ""
    ) -> Tuple[List[Dict[str, Any]], str]:
        """Newest-first page of summaries. The cursor is the ``seq`` of
        the last summary on the previous page (as a string); a page holds
        summaries strictly older than it. Empty next_cursor = exhausted."""
        traces = self.list()
        if cursor:
            after = int(cursor)
            traces = [
                t for t in traces if self._meta.get(t.trace_id, (0, ""))[0] < after
            ]
        if limit and limit > 0:
            page, more = traces[:limit], len(traces) > limit
        else:
            page, more = traces, False
        summaries = [self._summarize(t) for t in page]
        next_cursor = str(summaries[-1]["seq"]) if summaries and more else ""
        return summaries, next_cursor

    def retention_stats(self) -> Dict[str, Any]:
        """Seen/kept counts by verdict plus the sampling weight — the
        'how biased is the ring' answer. ``hit_rate`` is the fraction of
        interesting traces still retrievable (the bench's headline)."""
        with self._lock:
            seen = dict(sorted(self._seen.items()))
            kept = dict(sorted(self._kept.items()))
            interesting_seen = sum(
                n for v, n in seen.items() if v != "boring"
            )
            pinned = len(self._interesting)
            return {
                "seen": seen,
                "kept": kept,
                "sampled_out": self._sampled_out,
                "boring_weight": self._retention.boring_sample_n,
                "pinned": pinned,
                "hit_rate": round(pinned / interesting_seen, 4)
                if interesting_seen
                else 1.0,
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces) + len(self._interesting)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._interesting.clear()
            self._meta.clear()
            self._seq = 0
            self._seen.clear()
            self._kept.clear()
            self._sampled_out = 0


class _ActiveTrace:
    __slots__ = ("spans", "dropped", "open_spans")

    def __init__(self) -> None:
        self.spans: List[Span] = []
        self.dropped = 0
        self.open_spans = 0


class Tracer:
    # Per-trace span cap: the planner can fork hundreds of trials per
    # plan(); beyond this the trace keeps counting but stops keeping spans.
    MAX_SPANS_PER_TRACE = 4096
    # Live journey cap: journeys for pods that never bind are force-ended
    # oldest-first past this, so abandoned pods cannot leak roots.
    MAX_JOURNEYS = 512
    MAX_LINKS = 1024

    def __init__(self, capacity: int = 256) -> None:
        self.enabled = True
        self.store = TraceStore(capacity)
        self._lock = threading.Lock()
        # trace_id -> accumulating spans for traces whose root is open.
        self._active: Dict[str, _ActiveTrace] = {}
        # journey key -> open root span (insertion-ordered for eviction).
        self._journeys: Dict[Any, Span] = {}
        # link key -> span (cross-thread hand-off parents).
        self._links: Dict[Any, Span] = {}

    # ------------------------------------------------------- span lifecycle

    def start_span(
        self,
        name: str,
        parent: Optional[Span] = None,
        **attributes: Any,
    ) -> Span:
        if not self.enabled:
            return NOOP_SPAN
        if parent is None:
            parent = _current_span.get()
        if parent is NOOP_SPAN:
            parent = None
        elif parent is not None and parent.ended:
            # An ended parent is still a valid anchor (linked hand-offs
            # outlive the linking span) as long as its trace is reachable —
            # active or stored. Evicted trace: start fresh.
            with self._lock:
                reachable = parent.trace_id in self._active
            if not reachable and self.store.get(parent.trace_id) is None:
                parent = None
        if parent is None:
            trace_id = _new_id("t")
            parent_id = None
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=_new_id("s"),
            parent_id=parent_id,
            attributes=dict(attributes),
            start_wall=time.time(),
            start_perf=time.perf_counter(),
            thread=threading.current_thread().name,
        )
        if parent_id is None:
            with self._lock:
                self._active[trace_id] = _ActiveTrace()
                self._active[trace_id].open_spans += 1
        else:
            with self._lock:
                active = self._active.get(trace_id)
                if active is not None:
                    active.open_spans += 1
        return span

    def end_span(self, span: Span, status: Optional[str] = None) -> None:
        if span is NOOP_SPAN or span.ended:
            return
        span.duration_s = time.perf_counter() - span.start_perf
        if status is not None:
            span.status = status
        with self._lock:
            active = self._active.get(span.trace_id)
            if active is not None:
                active.open_spans = max(0, active.open_spans - 1)
                if len(active.spans) < self.MAX_SPANS_PER_TRACE:
                    active.spans.append(span)
                else:
                    active.dropped += 1
                if span.parent_id is None:
                    self._finalize_locked(span.trace_id)
                return
        # Late span: its trace already finalized (e.g. kubelet admission
        # landing after the journey ended at bind) — append to the stored
        # trace so the export still shows it.
        stored = self.store.get(span.trace_id)
        if stored is not None:
            if len(stored.spans) < self.MAX_SPANS_PER_TRACE:
                stored.spans.append(span)
            else:
                stored.dropped_spans += 1

    def _finalize_locked(self, trace_id: str) -> None:
        active = self._active.pop(trace_id, None)
        if active is None or not active.spans:
            return
        self.store.add(
            Trace(trace_id=trace_id, spans=active.spans, dropped_spans=active.dropped)
        )

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[Span] = None, **attributes: Any):
        """Context manager: open a span (implicitly parented on the active
        one unless ``parent`` is given), make it current, end it on exit.
        An exception marks status=error and re-raises."""
        span = self.start_span(name, parent=parent, **attributes)
        if span is NOOP_SPAN:
            yield span
            return
        token = _current_span.set(span)
        tid = threading.get_ident()
        prev_phase = _thread_phases.get(tid)
        _thread_phases[tid] = span.name
        try:
            yield span
        except BaseException:
            self.end_span(span, status="error")
            raise
        finally:
            if prev_phase is None:
                _thread_phases.pop(tid, None)
            else:
                _thread_phases[tid] = prev_phase
            _current_span.reset(token)
            self.end_span(span)

    def plugin_span(self, name: str, **attributes: Any):
        """Span for a scheduler-framework plugin call: no-ops while the
        planner's simulation suppression is active or no trace is open (a
        bare framework call outside any cycle should not mint root
        traces)."""
        if (
            not self.enabled
            or _plugins_suppressed.get()
            or _current_span.get() is None
        ):
            return contextlib.nullcontext(NOOP_SPAN)
        return self.span(name, **attributes)

    def current(self) -> Optional[Span]:
        span = _current_span.get()
        return None if span is NOOP_SPAN else span

    @contextlib.contextmanager
    def attach(self, span: Optional[Span]):
        """Make ``span`` the current span in this thread/context — the
        cross-thread propagation primitive (contextvars do not cross
        thread starts)."""
        token = _current_span.set(span)
        tid = threading.get_ident()
        prev_phase = _thread_phases.get(tid)
        if span is not None and span is not NOOP_SPAN:
            _thread_phases[tid] = span.name
        try:
            yield span
        finally:
            if prev_phase is None:
                _thread_phases.pop(tid, None)
            else:
                _thread_phases[tid] = prev_phase
            _current_span.reset(token)

    @contextlib.contextmanager
    def suppress_plugins(self):
        token = _plugins_suppressed.set(True)
        try:
            yield
        finally:
            _plugins_suppressed.reset(token)

    # ------------------------------------------------------------ journeys

    def journey_root(self, key: Any, name: str, **attributes: Any) -> Span:
        """Get-or-create the root span registered under ``key`` — the
        observe→bind trace anchor a later stage parents onto."""
        if not self.enabled:
            return NOOP_SPAN
        with self._lock:
            existing = self._journeys.get(key)
            if existing is not None and not existing.ended:
                return existing
        span = self.start_span(name, parent=NOOP_SPAN, **attributes)
        # parent=NOOP forces a fresh root even when called under an
        # unrelated active span (a controller's own reconcile span).
        with self._lock:
            raced = self._journeys.get(key)
            if raced is not None and not raced.ended:
                # Lost a creation race: keep the registered root, finalize
                # ours as an empty trace (no spans recorded yet).
                self._active.pop(span.trace_id, None)
                return raced
            self._journeys[key] = span
            evict = [
                k
                for k in list(self._journeys)[
                    : max(0, len(self._journeys) - self.MAX_JOURNEYS)
                ]
            ]
        for stale in evict:
            self.end_journey(stale, status="abandoned")
        return span

    def journey(self, key: Any) -> Optional[Span]:
        with self._lock:
            span = self._journeys.get(key)
        if span is None or span.ended:
            return None
        return span

    def end_journey(
        self, key: Any, status: str = "ok", **attributes: Any
    ) -> Optional[Span]:
        with self._lock:
            span = self._journeys.pop(key, None)
        if span is None or span is NOOP_SPAN:
            return None
        span.set_attributes(**attributes)
        self.end_span(span, status=status)
        return span

    # --------------------------------------------------------------- links

    def link(self, key: Any, span: Optional[Span]) -> None:
        """Register ``span`` as the parent for a future out-of-context
        continuation (e.g. node spec annotation → tpuagent reconcile)."""
        if span is None or span is NOOP_SPAN or not self.enabled:
            return
        with self._lock:
            self._links[key] = span
            while len(self._links) > self.MAX_LINKS:
                self._links.pop(next(iter(self._links)))

    def linked(self, key: Any, pop: bool = True) -> Optional[Span]:
        with self._lock:
            return self._links.pop(key, None) if pop else self._links.get(key)

    # --------------------------------------------------------------- admin

    def reset(self) -> None:
        """Test hook: drop all live and stored traces."""
        with self._lock:
            self._active.clear()
            self._journeys.clear()
            self._links.clear()
        self.store.clear()


# The process-wide tracer (the metrics.REGISTRY analogue).
TRACER = Tracer()

# The trace ring is bounded, but the health timeline still watches it:
# a ring that only ever grows toward its cap is fine, one that keeps
# growing past its cap means the bound broke. (Import placed after every
# definition: timeline.store reaches this module via the profiler, so a
# top-of-file import would be circular.)
from nos_tpu.timeline.sizes import SIZES as _SIZES  # noqa: E402

_SIZES.register("tracing.trace_store", lambda: len(TRACER.store))


# ------------------------------------------------------------------ logging


class TraceContextFilter(logging.Filter):
    """Injects the active trace/span id into every record, so existing
    ``nos_tpu.*`` log lines correlate with traces without touching any
    call site. Plain formatters can reference ``%(trace_id)s``."""

    def filter(self, record: logging.LogRecord) -> bool:
        span = _current_span.get()
        if span is None or span is NOOP_SPAN:
            record.trace_id = ""
            record.span_id = ""
        else:
            record.trace_id = span.trace_id
            record.span_id = span.span_id
        return True


class JsonLogFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, message, trace/span id
    (when a span is active), and exception text when present."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        trace_id = getattr(record, "trace_id", "")
        if trace_id:
            entry["trace_id"] = trace_id
            entry["span_id"] = getattr(record, "span_id", "")
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry, default=str)


def configure_logging(
    json_format: bool = False,
    level: Optional[int] = None,
    stream=None,
    logger_name: str = "nos_tpu",
) -> logging.Handler:
    """Attach a handler carrying the trace-context filter (and optionally
    the JSON formatter) to the ``nos_tpu`` logger tree. Returns the handler
    so callers/tests can detach it."""
    logger = logging.getLogger(logger_name)
    handler = logging.StreamHandler(stream)
    handler.addFilter(TraceContextFilter())
    if json_format:
        handler.setFormatter(JsonLogFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname)s %(name)s [%(trace_id)s] %(message)s"
            )
        )
    if level is not None:
        logger.setLevel(level)
    logger.addHandler(handler)
    return handler
