"""Pod predicates (reference pkg/util/pod/pod.go:15-88)."""
from __future__ import annotations

from nos_tpu.api.v1alpha1 import labels
from nos_tpu.kube.objects import Pod, PodPhase


def is_pending(pod: Pod) -> bool:
    return pod.status.phase == PodPhase.PENDING


def is_unschedulable(pod: Pod) -> bool:
    return is_pending(pod) and pod.unschedulable()


def is_preempting(pod: Pod) -> bool:
    return bool(pod.status.nominated_node_name)


def is_owned_by_daemonset(pod: Pod) -> bool:
    return pod.is_owned_by_kind("DaemonSet")


def is_owned_by_node(pod: Pod) -> bool:
    return pod.is_owned_by_kind("Node")


def extra_resources_could_help_scheduling(pod: Pod) -> bool:
    """The partitioner batches a pod only when re-partitioning could
    possibly make it schedulable (reference pod.go:25-33): it is pending and
    unschedulable, not already preempting its way onto a node, and not
    node-bound by a daemonset/static-pod owner."""
    return (
        is_unschedulable(pod)
        and not is_preempting(pod)
        and not is_owned_by_daemonset(pod)
        and not is_owned_by_node(pod)
    )


def is_over_quota(pod: Pod) -> bool:
    return pod.metadata.labels.get(labels.CAPACITY_LABEL) == labels.CAPACITY_OVER_QUOTA
