"""Loop-health rollup: busy meters plus the /debug/loops document.

Every control loop in the suite has the same shape — block for work, do
work, repeat — and the same failure mode under saturation: the busy
fraction pins at 1.0 while its watch queue's drain lag grows. This module
gives each loop a :class:`BusyMeter` (feeding the
``nos_tpu_controller_busy_fraction`` gauge) and a process-wide
:class:`LoopHealthRegistry` the loops register live stats callbacks with,
so ``/debug/loops`` can answer "which loop is behind and by how much" in
one document: per-loop busy fractions and queue depths, the store's
per-subscriber watch depths, and the saturation metric families
(drain lag, phase histograms, lock waits) from the registry snapshot.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from nos_tpu.util import metrics


class BusyMeter:
    """Windowed busy-fraction meter for one control loop.

    The loop reports each iteration's busy and idle time; once a window's
    total crosses ``WINDOW_SECONDS`` the gauge updates and the window
    resets — so the gauge tracks recent behavior, not the lifetime mean,
    and a loop that saturates shows up within about a second.
    """

    WINDOW_SECONDS = 1.0

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._window_busy = 0.0
        self._window_total = 0.0
        self._busy_total = 0.0
        self._iterations = 0
        self._fraction = 0.0
        self._gauge = metrics.CONTROLLER_BUSY.labels(loop=name)

    def record(self, busy_s: float, idle_s: float = 0.0) -> None:
        with self._lock:
            self._window_busy += busy_s
            self._window_total += busy_s + idle_s
            self._busy_total += busy_s
            if busy_s > 0:
                self._iterations += 1
            if self._window_total >= self.WINDOW_SECONDS:
                self._fraction = self._window_busy / self._window_total
                self._gauge.set(round(self._fraction, 4))
                self._window_busy = 0.0
                self._window_total = 0.0

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "busy_fraction": round(self._fraction, 4),
                "busy_seconds_total": round(self._busy_total, 4),
                "iterations": self._iterations,
            }


class LoopHealthRegistry:
    """Process-wide registry of live loop-stats callbacks (register on
    loop start, unregister on stop — a leaked registration would keep a
    dead loop in every later /debug/loops document)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loops: Dict[str, Callable[[], Dict[str, Any]]] = {}

    def register(self, name: str, stats_fn: Callable[[], Dict[str, Any]]) -> None:
        with self._lock:
            self._loops[name] = stats_fn

    def unregister(self, name: str) -> None:
        with self._lock:
            self._loops.pop(name, None)

    def names(self) -> list:
        with self._lock:
            return sorted(self._loops)

    def payload(self, store: Optional[Any] = None) -> Dict[str, Any]:
        """The /debug/loops JSON document."""
        with self._lock:
            loops = dict(self._loops)
        doc: Dict[str, Any] = {"generated_monotonic": time.monotonic(), "loops": {}}
        for name, stats_fn in sorted(loops.items()):
            try:
                doc["loops"][name] = stats_fn()
            except Exception as exc:
                doc["loops"][name] = {"error": f"{type(exc).__name__}: {exc}"}
        if store is not None and hasattr(store, "watch_stats"):
            doc["watchers"] = store.watch_stats()
        saturation_prefixes = (
            "nos_tpu_controller_busy_fraction",
            "nos_tpu_watch_drain_lag_seconds",
            "nos_tpu_watch_queue_depth",
            "nos_tpu_store_lock_",
            "nos_tpu_partitioner_phase_seconds",
            "nos_tpu_scheduler_phase_seconds",
            "nos_tpu_profiler_",
        )
        doc["metrics"] = {
            key: value
            for key, value in metrics.REGISTRY.snapshot().items()
            if key.startswith(saturation_prefixes)
        }
        return doc


# The process-wide loop registry (the metrics.REGISTRY analogue).
LOOPS = LoopHealthRegistry()
