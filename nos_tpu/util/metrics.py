"""Domain metrics: Prometheus-text-format registry.

The reference exposes only controller-runtime's default metrics and has no
domain counters — called out as a gap in SURVEY.md §5 ("no 'slices
created' counter") that the TPU build should fill. This registry backs the
north-star measurements: plans applied, slices created/deleted, pods
scheduled, schedule latency, preemptions, gang completions.

Metrics are label *families*: ``counter(name).labels(profile="2x2")``
returns a child series rendered as ``name{profile="2x2"}``. A family's
un-labeled parent still works (the pre-label call sites and tests), and
label values are escaped per the Prometheus text exposition format
(backslash, double quote, newline).

Fleet scale (the observability plane's own 100k-node story) adds three
mechanisms on top, all off by default:

- **Cardinality governor**: a per-family *series budget*
  (:meth:`MetricsRegistry.apply_series_budgets`). Once a family holds
  ``budget`` exact children, further distinct label sets aggregate into
  one ``_other``-valued child per label keyset and count (once per
  distinct refused set) into
  ``nos_tpu_metric_series_dropped_total{family}``. The mapping is a
  deterministic function of the admitted series set — for a fixed event
  stream (live or replayed) the same label sets land exact and the same
  sets fold into ``_other``, and counter sums are preserved exactly
  because the overflow child absorbs every refused increment.
- **Child delete**: ``remove(**labels)`` drops a child series from the
  family — the delete-reset path for per-object families (a deleted
  node's gauges disappear from the exposition instead of reporting
  stale values or zeros forever). ``LABEL_RESET_PATHS`` below registers
  which deleter owns each per-object family; the label-reset lint in
  ``tests/util/test_lint.py`` keys on it.
- **Incremental snapshot**: :meth:`MetricsRegistry.cursor` returns a
  :class:`SnapshotCursor` whose ``collect()`` yields only the series
  touched (and the keys removed) since the previous call — the timeline
  sampler's per-tick cost becomes O(changed series), not O(total).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

# The label value every refused series folds into, one overflow child
# per (family, label keyset). "_other" cannot collide with a Kubernetes
# object name (names may not start with "_").
OTHER_LABEL = "_other"


def escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping: ``\\`` → ``\\\\``,
    ``"`` → ``\\"``, newline → ``\\n``."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def render_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{key}="{escape_label_value(value)}"' for key, value in sorted(labels.items())
    )
    return "{" + inner + "}"


def _admit_child(family, label_values: Dict[str, str]):
    """labels() core shared by Counter/Gauge/Histogram: get-or-create the
    child for this label set. At or over the family's series budget a NEW
    label set routes to the family's ``_other`` child for the same label
    keys instead — admission depends only on which sets already exist, so
    a replayed event stream reproduces the same exact/overflow split."""
    if family._label_values:
        raise ValueError(f"{family.name}: labels() on an already-labeled child")
    key = tuple(sorted((k, str(v)) for k, v in label_values.items()))
    dropped_new = False
    with family._lock:
        child = family._children.get(key)
        if child is None:
            budget = family._budget
            exact = len(family._children) - family._overflow_children
            if budget is not None and exact >= budget:
                refused = hash(key)
                if refused not in family._dropped_hashes:
                    family._dropped_hashes.add(refused)
                    dropped_new = True
                okey = tuple((k, OTHER_LABEL) for k, _ in key)
                child = family._children.get(okey)
                if child is None:
                    child = family._new_child({k: OTHER_LABEL for k, _ in key})
                    child._is_overflow = True
                    family._children[okey] = child
                    family._overflow_children += 1
                    family._children_sorted = None
            else:
                child = family._new_child(
                    {k: str(v) for k, v in label_values.items()}
                )
                family._children[key] = child
                family._children_sorted = None
    if dropped_new and family._on_drop is not None:
        family._on_drop(family.name)
    return child


class Counter:
    TYPE = "counter"

    def __init__(
        self, name: str, help_text: str, label_values: Optional[Dict[str, str]] = None
    ) -> None:
        self.name = name
        self.help = help_text
        self._value = 0.0
        self._lock = threading.Lock()
        # Family support: parent holds children keyed by sorted label
        # items; a child holds its own label values and no children.
        self._label_values: Dict[str, str] = dict(label_values or {})
        # Label sets are fixed at creation, so the rendered suffix is too
        # (snapshot() runs on every timeline sample — keep it flat).
        self._label_suffix = render_labels(self._label_values)
        self._snapshot_key = f"{name}{self._label_suffix}"
        self._children: Dict[Tuple, "Counter"] = {}
        self._children_sorted: Optional[list] = None
        self._touched = False
        # Governor state (parent only): None = unbudgeted. Overflow
        # children ("_other") are exempt from the budget; refused label
        # sets are remembered as 64-bit hashes so the dropped count is
        # per-distinct-series without paying a full child per refusal.
        self._budget: Optional[int] = None
        self._overflow_children = 0
        self._dropped_hashes: Set[int] = set()
        self._is_overflow = False
        # Registry hooks: _mark feeds the incremental-snapshot dirty set
        # (wired only while cursors exist, so the no-cursor fast path is
        # unchanged), _mark_removed propagates child deletes to cursors,
        # _on_drop counts governor refusals.
        self._mark = None
        self._mark_removed = None
        self._on_drop = None

    def _new_child(self, label_values: Dict[str, str]) -> "Counter":
        child = type(self)(self.name, self.help, label_values)
        child._mark = self._mark
        return child

    def labels(self, **label_values: str) -> "Counter":
        """Child series for this label set (created on first use, subject
        to the family's series budget — see :data:`OTHER_LABEL`)."""
        return _admit_child(self, label_values)

    def remove(self, **label_values: str) -> bool:
        """Delete the child series for this label set (the delete-reset
        path for per-object families). Returns False if absent. The freed
        slot counts against the budget again; the dropped-series record
        is monotonic and stays."""
        key = tuple(sorted((k, str(v)) for k, v in label_values.items()))
        with self._lock:
            child = self._children.pop(key, None)
            if child is None:
                return False
            if child._is_overflow:
                self._overflow_children -= 1
            self._children_sorted = None
        if self._mark_removed is not None:
            self._mark_removed(child)
        return True

    def _removed_snapshot_keys(self) -> Tuple[str, ...]:
        return (self._snapshot_key,)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount
            self._touched = True
        if self._mark is not None:
            self._mark(self)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def total(self) -> float:
        """Own value plus every labeled child — the family aggregate."""
        with self._lock:
            children = list(self._children.values())
            own = self._value
        return own + sum(c.value for c in children)

    def _sorted_children(self):
        with self._lock:
            if self._children_sorted is None:
                self._children_sorted = [
                    child for _, child in sorted(self._children.items())
                ]
            return self._children_sorted

    def _sample_lines(self) -> list:
        lines = []
        with self._lock:
            bare = self._touched or not self._children
            value = self._value
            labels = render_labels(self._label_values)
        if bare:
            lines.append(f"{self.name}{labels} {value}")
        return lines

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.TYPE}",
        ]
        lines.extend(self._sample_lines())
        for child in self._sorted_children():
            with child._lock:
                labels = render_labels(child._label_values)
                lines.append(f"{child.name}{labels} {child._value}")
        return "\n".join(lines) + "\n"

    def snapshot_self_into(self, out: Dict[str, float]) -> None:
        """This series' own sample only (no children) — the unit the
        incremental snapshot cursor collects per dirty series."""
        with self._lock:
            touched = self._touched
            value = self._value
        if touched:
            out[self._snapshot_key] = value

    def snapshot_into(self, out: Dict[str, float]) -> None:
        """Touched series only: a family nothing has incremented yet has
        no sample worth a timeline series (it appears on first use, the
        same way labeled children do)."""
        self.snapshot_self_into(out)
        if self._children:
            for child in self._sorted_children():
                child.snapshot_into(out)


class Gauge(Counter):
    TYPE = "gauge"

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self._touched = True
        if self._mark is not None:
            self._mark(self)


class Histogram:
    DEFAULT_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

    # Percentiles are computed from a bounded window of recent observations
    # so a long-running scheduler never grows memory; counts/sum/buckets
    # stay exact forever.
    WINDOW = 1024

    def __init__(
        self,
        name: str,
        help_text: str,
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
        label_values: Optional[Dict[str, str]] = None,
    ) -> None:
        from collections import deque

        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._recent = deque(maxlen=self.WINDOW)
        # Sorted-window cache for percentile(): rebuilt lazily after an
        # observe invalidates it, so quiet histograms cost nothing to
        # snapshot (the timeline sampler snapshots every family each
        # interval — most are idle at any given moment).
        self._ordered: Optional[list] = None
        self._lock = threading.Lock()
        self._label_values: Dict[str, str] = dict(label_values or {})
        self._label_suffix = render_labels(self._label_values)
        self._snapshot_keys = tuple(
            f"{name}_{part}{self._label_suffix}"
            for part in ("count", "sum", "p50", "p95", "p99")
        )
        self._children: Dict[Tuple, "Histogram"] = {}
        self._children_sorted: Optional[list] = None
        self._touched = False
        self._budget: Optional[int] = None
        self._overflow_children = 0
        self._dropped_hashes: Set[int] = set()
        self._is_overflow = False
        self._mark = None
        self._mark_removed = None
        self._on_drop = None

    def _new_child(self, label_values: Dict[str, str]) -> "Histogram":
        child = Histogram(self.name, self.help, self.buckets, label_values)
        child._mark = self._mark
        return child

    def labels(self, **label_values: str) -> "Histogram":
        return _admit_child(self, label_values)

    def remove(self, **label_values: str) -> bool:
        """Delete the child series for this label set (see Counter.remove)."""
        key = tuple(sorted((k, str(v)) for k, v in label_values.items()))
        with self._lock:
            child = self._children.pop(key, None)
            if child is None:
                return False
            if child._is_overflow:
                self._overflow_children -= 1
            self._children_sorted = None
        if self._mark_removed is not None:
            self._mark_removed(child)
        return True

    def _removed_snapshot_keys(self) -> Tuple[str, ...]:
        return self._snapshot_keys

    def observe(self, value: float) -> None:
        with self._lock:
            self._touched = True
            self._sum += value
            self._count += 1
            self._recent.append(value)
            self._ordered = None
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1
        if self._mark is not None:
            self._mark(self)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, p: float) -> Optional[float]:
        with self._lock:
            if not self._recent:
                return None
            if self._ordered is None:
                self._ordered = sorted(self._recent)
            ordered = self._ordered
            index = min(len(ordered) - 1, int(p / 100.0 * len(ordered)))
            return ordered[index]

    def _sorted_children(self):
        with self._lock:
            if self._children_sorted is None:
                self._children_sorted = [
                    child for _, child in sorted(self._children.items())
                ]
            return self._children_sorted

    def _sample_lines(self) -> list:
        with self._lock:
            if not (self._touched or not self._children):
                return []
            lines = []
            base = dict(self._label_values)
            cumulative = 0
            for bound, count in zip(self.buckets, self._counts):
                cumulative += count
                labels = render_labels({**base, "le": str(bound)})
                lines.append(f"{self.name}_bucket{labels} {cumulative}")
            cumulative += self._counts[-1]
            labels = render_labels({**base, "le": "+Inf"})
            lines.append(f"{self.name}_bucket{labels} {cumulative}")
            suffix = render_labels(base)
            lines.append(f"{self.name}_sum{suffix} {self._sum}")
            lines.append(f"{self.name}_count{suffix} {self._count}")
            return lines

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        lines.extend(self._sample_lines())
        for child in self._sorted_children():
            lines.extend(child._sample_lines())
        return "\n".join(lines) + "\n"

    def snapshot_self_into(self, out: Dict[str, float]) -> None:
        """Count/sum always (an empty histogram's exact zeros are part of
        the exposition contract); percentiles only once samples exist,
        computed off one lock hold and the shared sorted-window cache."""
        key_count, key_sum, key_p50, key_p95, key_p99 = self._snapshot_keys
        with self._lock:
            out[key_count] = self._count
            out[key_sum] = self._sum
            if self._recent:
                if self._ordered is None:
                    self._ordered = sorted(self._recent)
                ordered = self._ordered
                last = len(ordered) - 1
                for p, key in ((50, key_p50), (95, key_p95), (99, key_p99)):
                    out[key] = ordered[min(last, int(p / 100.0 * len(ordered)))]

    def snapshot_into(self, out: Dict[str, float]) -> None:
        self.snapshot_self_into(out)
        if self._children:
            for child in self._sorted_children():
                child.snapshot_into(out)


class SnapshotCursor:
    """Incremental registry snapshot: ``collect()`` returns ``(changed,
    removed_keys)`` since the previous call — O(series touched in the
    window), not O(total series). The first call primes with the full
    snapshot. Mutator ordering makes the delta lossless: a series updates
    its value *before* marking itself dirty, and the drain swaps the
    dirty set *before* reading values, so any update whose mark lands in
    an already-drained set was visible to that drain's reads (duplicates
    across windows are possible, losses are not)."""

    def __init__(self, registry: "MetricsRegistry") -> None:
        self._registry = registry
        self._lock = threading.Lock()
        self._pending: Set[object] = set()
        self._removed: Set[str] = set()
        self._primed = False

    def collect(self) -> Tuple[Dict[str, float], List[str]]:
        reg = self._registry
        if not self._primed:
            with self._lock:
                self._primed = True
                self._pending.clear()
                self._removed.clear()
            return reg.snapshot(), []
        reg._drain_dirty()
        with self._lock:
            pending, self._pending = self._pending, set()
            removed = sorted(self._removed)
            self._removed.clear()
        out: Dict[str, float] = {}
        for series in pending:
            series.snapshot_self_into(out)
        # A series both mutated and removed in the window: the removal
        # wins — its key must not resurface as a change.
        for key in removed:
            out.pop(key, None)
        return out, removed

    def close(self) -> None:
        """Detach from the registry (stop accumulating deltas)."""
        reg = self._registry
        with reg._dirty_lock:
            if self in reg._cursors:
                reg._cursors.remove(self)


METRIC_SERIES_DROPPED_NAME = "nos_tpu_metric_series_dropped_total"


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()
        # Incremental-snapshot plumbing: series objects touched since the
        # last drain, merged into every attached cursor's pending set.
        # _marking stays False until the first cursor attaches, so the
        # inc/set/observe fast path pays nothing by default.
        self._dirty: Set[object] = set()
        self._dirty_lock = threading.Lock()
        self._cursors: List[SnapshotCursor] = []
        self._marking = False
        # Governor budgets for families not created yet (apply before
        # definition, e.g. config load before a lazy import).
        self._pending_budgets: Dict[str, Optional[int]] = {}
        self._default_budget: Optional[int] = None

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_text))

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_text))

    def histogram(self, name: str, help_text: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help_text, buckets))

    def _get_or_create(self, name: str, factory):
        with self._lock:
            if name not in self._metrics:
                metric = factory()
                metric._mark_removed = self._mark_removed
                metric._on_drop = self._note_dropped
                if name != METRIC_SERIES_DROPPED_NAME:
                    metric._budget = self._pending_budgets.get(
                        name, self._default_budget
                    )
                if self._marking:
                    metric._mark = self._mark_dirty
                self._metrics[name] = metric
            return self._metrics[name]

    # ----------------------------------------------- incremental snapshot

    def _mark_dirty(self, series) -> None:
        with self._dirty_lock:
            self._dirty.add(series)

    def _mark_removed(self, series) -> None:
        keys = series._removed_snapshot_keys()
        with self._dirty_lock:
            self._dirty.discard(series)
            for cursor in self._cursors:
                with cursor._lock:
                    cursor._removed.update(keys)

    def _drain_dirty(self) -> None:
        with self._dirty_lock:
            if not self._dirty:
                return
            drained, self._dirty = self._dirty, set()
            cursors = list(self._cursors)
        for cursor in cursors:
            with cursor._lock:
                cursor._pending |= drained

    def cursor(self) -> SnapshotCursor:
        """Attach an incremental-snapshot consumer (each cursor sees every
        delta independently). Call ``close()`` when done."""
        cursor = SnapshotCursor(self)
        with self._lock:
            metrics = list(self._metrics.values())
        with self._dirty_lock:
            self._cursors.append(cursor)
            self._marking = True
        for metric in metrics:
            metric._mark = self._mark_dirty
            with metric._lock:
                children = list(metric._children.values())
            for child in children:
                child._mark = self._mark_dirty
        return cursor

    # ------------------------------------------------ cardinality governor

    def _note_dropped(self, family: str) -> None:
        self.counter(
            METRIC_SERIES_DROPPED_NAME,
            "Distinct label sets refused by a family's series budget and "
            "folded into its _other child (by family)",
        ).labels(family=family).inc()

    def apply_series_budgets(
        self,
        budgets: Optional[Dict[str, int]] = None,
        default: Optional[int] = None,
    ) -> dict:
        """Set per-family series budgets (``default`` applies to every
        family without an explicit entry; None leaves it unbudgeted).
        Budgets gate NEW admissions only — children already past the
        budget are grandfathered. Returns the previous budget state for
        :meth:`restore_series_budgets` (the chaos harness applies budgets
        around a run and must leave the process registry untouched)."""
        budgets = dict(budgets or {})
        budgets.pop(METRIC_SERIES_DROPPED_NAME, None)
        with self._lock:
            metrics = dict(self._metrics)
            prev = {
                "default": self._default_budget,
                "pending": dict(self._pending_budgets),
                "families": {
                    name: metric._budget for name, metric in metrics.items()
                },
            }
            self._default_budget = default
            self._pending_budgets = dict(budgets)
        for name, metric in metrics.items():
            if name == METRIC_SERIES_DROPPED_NAME:
                continue
            metric._budget = budgets.get(name, default)
        return prev

    def restore_series_budgets(self, prev: dict) -> None:
        with self._lock:
            metrics = dict(self._metrics)
            self._default_budget = prev["default"]
            self._pending_budgets = dict(prev["pending"])
        for name, budget in prev["families"].items():
            metric = metrics.get(name)
            if metric is not None:
                metric._budget = budget

    def series_report(self) -> Dict[str, dict]:
        """Per-family series accounting — exact children, overflow
        children, distinct refused label sets, and the budget in force.
        The bench and /debug surfaces read this; only families with
        children or a budget appear."""
        with self._lock:
            metrics = dict(self._metrics)
        out: Dict[str, dict] = {}
        for name in sorted(metrics):
            metric = metrics[name]
            with metric._lock:
                total = len(metric._children)
                overflow = metric._overflow_children
                dropped = len(metric._dropped_hashes)
                budget = metric._budget
            if total or budget is not None:
                out[name] = {
                    "exact": total - overflow,
                    "overflow": overflow,
                    "dropped": dropped,
                    "budget": budget,
                }
        return out

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "".join(m.render() for m in sorted(metrics, key=lambda m: m.name))

    def snapshot(self) -> Dict[str, float]:
        """Flat name→value map (labeled series keyed ``name{k="v"}``;
        histograms expand to ``_count``/``_sum``/``_p50``/``_p95``/``_p99``)
        — the JSON shape /debug/vars serves."""
        with self._lock:
            metrics = dict(self._metrics)
        out: Dict[str, float] = {}
        for metric in metrics.values():
            metric.snapshot_into(out)
        return out


# The process-wide registry (controller-runtime's metrics.Registry analogue).
REGISTRY = MetricsRegistry()

PLANS_APPLIED = REGISTRY.counter(
    "nos_tpu_partitioning_plans_applied_total", "Partitioning plans actuated"
)
DIVERGENCE_REPLANS = REGISTRY.counter(
    "nos_tpu_partitioning_divergence_replans_total",
    "Immediate replans triggered by actuation diverging from spec",
)
BOARD_RESERVATIONS = REGISTRY.counter(
    "nos_tpu_board_reservations_total",
    "Nodes reserved to drain for full-board pods",
)
SLICES_CREATED = REGISTRY.counter(
    "nos_tpu_slices_created_total", "TPU slices carved by agents (by profile)"
)
SLICES_DELETED = REGISTRY.counter(
    "nos_tpu_slices_deleted_total", "TPU slices destroyed by agents (by profile)"
)
PODS_SCHEDULED = REGISTRY.counter(
    "nos_tpu_pods_scheduled_total", "Pods bound by the scheduler (by namespace)"
)
PREEMPTIONS = REGISTRY.counter(
    "nos_tpu_preemptions_total",
    "Pods evicted by quota preemption (by victim namespace)",
)
GANGS_SCHEDULED = REGISTRY.counter(
    "nos_tpu_gangs_scheduled_total", "Gangs released for binding"
)
SCHEDULE_LATENCY = REGISTRY.histogram(
    "nos_tpu_schedule_latency_seconds",
    "Per-pod scheduling cycle latency (by namespace)",
)
FILTER_REJECTIONS = REGISTRY.counter(
    "nos_tpu_scheduler_filter_rejections_total",
    "Scheduling-cycle rejections by the plugin that refused (by plugin)",
)
SCHEDULING_UNSCHEDULABLE = REGISTRY.counter(
    "nos_tpu_scheduling_unschedulable_total",
    "Per-node rejections behind failed scheduling cycles, by rejecting "
    "plugin and normalized reason (the Diagnosis ledger, aggregated)",
)

# Partitioner planning loop (the nos_scheduling_latency north star). The
# fork/revert/commit counters plus the nodes-copied gauge make the CoW
# snapshot's touched-node economics visible in scraped metrics: nodes
# copied per fork should hover near 1 regardless of cluster size, and a
# regression back toward O(cluster) copying shows up immediately.
PLAN_DURATION = REGISTRY.histogram(
    "nos_tpu_plan_duration_seconds",
    "Planner.plan() wall time per invocation",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)
SNAPSHOT_FORKS = REGISTRY.counter(
    "nos_tpu_snapshot_forks_total", "Snapshot forks started by the planner"
)
SNAPSHOT_COMMITS = REGISTRY.counter(
    "nos_tpu_snapshot_commits_total", "Snapshot forks committed (trial kept)"
)
SNAPSHOT_REVERTS = REGISTRY.counter(
    "nos_tpu_snapshot_reverts_total", "Snapshot forks reverted (trial discarded)"
)
SNAPSHOT_NODES_COPIED = REGISTRY.counter(
    "nos_tpu_snapshot_nodes_copied_total",
    "SnapshotNodes cloned into fork journals (CoW touched-node copies)",
)
FORK_NODES_COPIED = REGISTRY.gauge(
    "nos_tpu_snapshot_fork_nodes_copied",
    "Nodes cloned by the most recently ended fork (commit or revert)",
)
TRACKER_TOTALS_RECOMPUTES = REGISTRY.counter(
    "nos_tpu_tracker_totals_recomputes_total",
    "SliceTracker lacking_totals cache misses (full per-accelerator sums)",
)
TRACKER_TOTALS_INCREMENTAL = REGISTRY.counter(
    "nos_tpu_tracker_totals_incremental_total",
    "SliceTracker lacking_totals calls served from the incremental cache",
)
PLAN_VERDICT_CACHE = REGISTRY.counter(
    "nos_tpu_plan_verdict_cache_total",
    "Planner verdict-cache lookups by outcome (event=hit|miss|bypass); "
    "flushed once per plan() to keep lock traffic off the trial hot path",
)
PLAN_CARVE_FUTILITY = REGISTRY.counter(
    "nos_tpu_plan_carve_futility_total",
    "Carve attempts skipped because a (node version, lacking signature) "
    "memo already proved them futile; flushed once per plan()",
)
PLAN_MODE = REGISTRY.counter(
    "nos_tpu_plan_mode_total",
    "Planner.plan() invocations by execution mode "
    "(mode=incremental|full|fallback): incremental prunes-and-reuses the "
    "previous cycle's memos over a persistent base snapshot, fallback "
    "replans from scratch but preserves the base (cold start, oversized "
    "dirty set, shape/quota change), full is the legacy "
    "snapshot-consuming path",
)
PLAN_POOL_COUNT = REGISTRY.gauge(
    "nos_tpu_plan_pool_count",
    "Independent planning pools the most recent sharded cycle "
    "partitioned the cluster into (by kind); 1 means the pool graph was "
    "connected (mega-pool) or sharding is off",
)
PLAN_POOL_DURATION = REGISTRY.histogram(
    "nos_tpu_plan_pool_duration_seconds",
    "Per-pool Planner.plan() wall time within a sharded cycle (by pool)",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
)
PLAN_MERGE_CONFLICTS = REGISTRY.counter(
    "nos_tpu_plan_merge_conflicts_total",
    "Sharded cycles whose cross-pool merge invariants failed (a node "
    "claimed twice, a node unplanned, a board listed twice, or physical "
    "capacity exceeded); the cycle's plan is discarded and the next "
    "cycle rebuilds the partition from scratch",
)
PLAN_WORKER_RESTARTS = REGISTRY.counter(
    "nos_tpu_plan_worker_restarts_total",
    "Pool-planner worker processes dropped and respawned from a fresh "
    "wire image (crash, wedge past the cycle timeout, untrusted frame, "
    "or codec-version rejection); each drop escalates that pool to "
    "in-parent serial planning for the cycle",
)
PLAN_WORKER_RTT = REGISTRY.histogram(
    "nos_tpu_plan_worker_rtt_seconds",
    "Per-pool round-trip of one process-backend plan cycle as the parent "
    "sees it: delta frame out to plan reply in (includes worker queueing, "
    "refresh, plan, and serialization)",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
)
PLAN_BACKEND = REGISTRY.counter(
    "nos_tpu_plan_backend_total",
    "Sharded pool-plan executions by backend "
    "(backend=serial|thread|process|escalated): escalated counts pools a "
    "process cycle had to plan in-parent because their worker was dead, "
    "wedged, or not yet bootstrapped",
)
WARM_BOOT_OUTCOME = REGISTRY.counter(
    "nos_tpu_warm_boot_outcome_total",
    "Warm-state adoption attempts at startup/full-rebuild by outcome "
    "(outcome=adopted|partial|cold): adopted = every node's signature "
    "matched, partial = some matched, cold = no usable warm state",
)
MULTIHOST_EXPANSIONS = REGISTRY.counter(
    "nos_tpu_multihost_expansions_total",
    "Oversized chip requests expanded into multi-host slice gangs",
)
WEBHOOK_DENIALS = REGISTRY.counter(
    "nos_tpu_webhook_denials_total",
    "AdmissionReview requests the validating webhooks denied",
)
LEADER_TRANSITIONS = REGISTRY.counter(
    "nos_tpu_leader_transitions_total",
    "Leadership acquisitions across all components' leases",
)
WATCH_RECONNECTS = REGISTRY.counter(
    "nos_tpu_watch_reconnects_total",
    "Informer watch streams re-established after an error, disconnect, "
    "or 410 expiry (by kind)",
)

# Serving engine (a replica exports these next to the control-plane set).
SERVE_REQUESTS = REGISTRY.counter(
    "nos_tpu_serve_requests_total", "Requests completed by the serving engine"
)
SERVE_TOKENS = REGISTRY.counter(
    "nos_tpu_serve_tokens_total", "Tokens generated by the serving engine"
)
SERVE_TICKS = REGISTRY.counter(
    "nos_tpu_serve_decode_ticks_total",
    "Batched decode ticks executed (each reads the weights once)",
)
SERVE_SLOT_TICKS_ACTIVE = REGISTRY.counter(
    "nos_tpu_serve_slot_ticks_active_total",
    "Per-slot ticks spent on live requests (active / (ticks*slots) = "
    "batch occupancy)",
)
SERVE_PREFIX_HITS = REGISTRY.counter(
    "nos_tpu_serve_prefix_cache_hits_total",
    "Chunked admissions that reused a cached prompt-prefix K/V",
)
SERVE_PREFIX_TOKENS_REUSED = REGISTRY.counter(
    "nos_tpu_serve_prefix_tokens_reused_total",
    "Prompt tokens whose prefill was skipped via the prefix cache",
)
SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "nos_tpu_serve_queue_depth", "Requests waiting for a free slot"
)
SERVE_SLOTS = REGISTRY.gauge(
    "nos_tpu_serve_slots", "Configured slot count (the occupancy denominator)"
)

# Per-request serving latency (serve/telemetry.py): observed at retire
# from the request's journey stamps, labeled model/adapter/bucket so tail
# latency decomposes by tenant and prompt-length class. Stamps come from
# the engine's ServeClock — wall time live, virtual time under the
# deterministic bench driver (slo/driver.py).
_SERVE_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
SERVE_TTFT = REGISTRY.histogram(
    "nos_tpu_serve_ttft_seconds",
    "Time to first token: submit to the first token EMITTED to the host "
    "(includes queue wait, prefill, and — under deferred admission "
    "resolution — the first decode chunk's sync) "
    "(by model, adapter, bucket)",
    buckets=_SERVE_LATENCY_BUCKETS,
)
SERVE_TPOT = REGISTRY.histogram(
    "nos_tpu_serve_tpot_seconds",
    "Time per output token: (last token - first token) / (tokens - 1); "
    "single-token completions do not observe (by model, adapter, bucket)",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
)
SERVE_E2E = REGISTRY.histogram(
    "nos_tpu_serve_e2e_seconds",
    "End-to-end request latency, submit to retire "
    "(by model, adapter, bucket)",
    buckets=_SERVE_LATENCY_BUCKETS,
)
SERVE_QUEUE_WAIT = REGISTRY.histogram(
    "nos_tpu_serve_queue_wait_seconds",
    "Submit-to-admission wait for a free slot (by model, adapter, bucket)",
    buckets=_SERVE_LATENCY_BUCKETS,
)
SERVE_REQUEST_TOKENS_PER_S = REGISTRY.histogram(
    "nos_tpu_serve_request_tokens_per_second",
    "Per-request decode throughput: tokens / e2e latency "
    "(by model, adapter, bucket)",
    buckets=(1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0),
)
SERVE_GOODPUT_REQUESTS = REGISTRY.counter(
    "nos_tpu_serve_goodput_requests_total",
    "Completed requests by latency verdict (verdict=good|late: good met "
    "the engine's per-request TTFT/e2e targets, typically derived from "
    "the SLO specs) (by model)",
)
SERVE_GOODPUT_TOKENS = REGISTRY.counter(
    "nos_tpu_serve_goodput_tokens_total",
    "Tokens from requests that met their latency targets — the goodput "
    "numerator next to nos_tpu_serve_tokens_total's raw throughput "
    "(by model)",
)

# Speculative decoding (serve/spec_engine.py): acceptance telemetry. The
# accept RATE is accepted/proposed; tokens-per-round parity with
# stats()['mean_accepted'] is accepted/rounds over active row-rounds.
SERVE_SPEC_ROUNDS = REGISTRY.counter(
    "nos_tpu_serve_spec_rounds_total",
    "Speculative rounds executed per active row (row-rounds): each "
    "drafts k tokens and commits 1..k+1",
)
SERVE_SPEC_DRAFT_TOKENS = REGISTRY.counter(
    "nos_tpu_serve_spec_draft_tokens_total",
    "Draft tokens proposed to the target verifier (k per active "
    "row-round)",
)
SERVE_SPEC_ACCEPTED_TOKENS = REGISTRY.counter(
    "nos_tpu_serve_spec_accepted_tokens_total",
    "Draft tokens the target accepted (committed - 1 per active "
    "row-round; the bonus token is not a draft acceptance)",
)

# Flight recorder / invariant auditor (record/).
AUDIT_VIOLATIONS = REGISTRY.counter(
    "nos_tpu_audit_violations_total",
    "Invariant-auditor checks whose shadow recompute disagreed with the "
    "incremental structure (verdict cache, lacking totals, free pool, "
    "mutation clock, carve-futility memo, capacity ledger) (by check)",
)

# Chaos harness (chaos/).
CHAOS_FAULTS = REGISTRY.counter(
    "nos_tpu_chaos_faults_total",
    "Faults injected by the chaos driver (by kind)",
)
CHAOS_CONVERGENCE = REGISTRY.histogram(
    "nos_tpu_chaos_convergence_seconds",
    "Wall time from end-of-burst heal to all convergence oracles passing",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 15.0, 30.0, 60.0),
)

# Capacity ledger (capacity/): live time-weighted chip-seconds accounting.
CAPACITY_CHIP_SECONDS = REGISTRY.counter(
    "nos_tpu_capacity_chip_seconds_total",
    "Chip-seconds integrated between control-cycle observations, by "
    "state=busy|no-demand|pending-unschedulable|reconfig|reserved-by-gang"
    "|autoscaler-grace (idle states attribute where idle time went; "
    "reason carries the dominant carve-failure prefix for "
    "pending-unschedulable)",
)
CAPACITY_UTILIZATION = REGISTRY.gauge(
    "nos_tpu_capacity_utilization",
    "Cumulative cluster utilization: busy chip-seconds / total "
    "chip-seconds since the ledger started",
)
CAPACITY_IDLE_PENDING_FRACTION = REGISTRY.gauge(
    "nos_tpu_capacity_idle_pending_fraction",
    "Share of total chip-seconds spent idle while unbound pending TPU "
    "demand existed (the scheduling-inefficiency meter of ROADMAP item 2)",
)
CAPACITY_NODE_CHIPS = REGISTRY.gauge(
    "nos_tpu_capacity_node_chips",
    "Instantaneous per-node chip counts (by node, state=total|used|free); "
    "series are removed when the node is deleted",
)
NODE_FRAGMENTATION = REGISTRY.gauge(
    "nos_tpu_node_fragmentation_index",
    "Per-node fragmentation: 1 - largest-carveable-slice / free-chips "
    "from the reported slice geometry (0 = a pending job as large as the "
    "free space could still be carved)",
)
CLUSTER_FRAGMENTATION = REGISTRY.gauge(
    "nos_tpu_cluster_fragmentation_index",
    "Free-chip-weighted mean of the per-node fragmentation indices",
)
GANG_WAIT_SECONDS = REGISTRY.histogram(
    "nos_tpu_gang_wait_seconds",
    "Gang wait from arrival, by stage=first_feasible|bound (first_feasible "
    "= the first cycle the whole gang found nodes; bound = released for "
    "binding)",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 15.0, 30.0, 60.0),
)
QUOTA_BORROWED_CHIPS = REGISTRY.gauge(
    "nos_tpu_quota_borrowed_chips",
    "Chips a namespace uses beyond its ElasticQuota min (by namespace)",
)
QUOTA_STARVED_CHIPS = REGISTRY.gauge(
    "nos_tpu_quota_starved_chips",
    "Chips of guaranteed ElasticQuota min a namespace is short of while "
    "it has pending demand (by namespace)",
)

# Model autoscaler (controllers/autoscaler/): burn-rate-driven replica
# scaling of ModelServing objects.
AUTOSCALER_REPLICAS = REGISTRY.gauge(
    "nos_tpu_autoscaler_replicas",
    "Replica counts per ModelServing (by model, state=desired|ready)",
)
AUTOSCALER_DECISIONS = REGISTRY.counter(
    "nos_tpu_autoscaler_decisions_total",
    "Autoscaler policy verdicts per reconcile "
    "(by verdict=hold|scale-up|scale-down|scale-to-zero|cold-start)",
)
AUTOSCALER_COLD_START_SECONDS = REGISTRY.histogram(
    "nos_tpu_autoscaler_cold_start_seconds",
    "Time from a scaled-to-zero model's wake decision to its first "
    "replica binding to a node (carve wait included)",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 15.0, 30.0, 60.0),
)

# Control-plane saturation telemetry (util/loop_health.py, util/profiling.py,
# kube/store.py): where a control cycle's wall time goes, how far behind the
# watch queues run, and what the store lock costs — the inward-facing
# counterpart of the capacity ledger's outward accounting.
CONTROLLER_BUSY = REGISTRY.gauge(
    "nos_tpu_controller_busy_fraction",
    "Fraction of the last ~1 s window a control loop spent doing work "
    "rather than waiting for it (by loop)",
)
WATCH_DRAIN_LAG = REGISTRY.histogram(
    "nos_tpu_watch_drain_lag_seconds",
    "Age of a WatchEvent at dequeue — monotonic enqueue-to-drain delay "
    "per consuming loop (by consumer); a growing lag means the consumer "
    "is saturated",
    buckets=(
        0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
    ),
)
WATCH_QUEUE_DEPTH = REGISTRY.gauge(
    "nos_tpu_watch_queue_depth",
    "Events waiting in a watch subscriber's (unbounded) queue "
    "(by kind_set: the subscriber's name, or its joined kind set when "
    "anonymous)",
)
STORE_LOCK_WAIT = REGISTRY.counter(
    "nos_tpu_store_lock_wait_seconds_total",
    "Seconds callers spent blocked on the KubeStore lock. Sampled at "
    "contention: the uncontended fast path records nothing, so this "
    "counts only acquisitions that actually waited",
)
STORE_LOCK_CONTENTION = REGISTRY.counter(
    "nos_tpu_store_lock_contention_total",
    "KubeStore lock acquisitions that had to wait for another holder",
)
PARTITIONER_PHASE = REGISTRY.histogram(
    "nos_tpu_partitioner_phase_seconds",
    "Partitioner cycle phase durations "
    "(by kind, phase=drain|refresh|plan|actuate; a full rebuild lands in "
    "refresh)",
    buckets=(
        0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    ),
)
SCHEDULER_PHASE = REGISTRY.histogram(
    "nos_tpu_scheduler_phase_seconds",
    "Scheduler cycle phase durations (phase=decide|settle: decide is the "
    "in-memory pipeline through Permit, settle the bind/nominate/fail "
    "store writes)",
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
)
PROFILER_SAMPLES = REGISTRY.counter(
    "nos_tpu_profiler_samples_total",
    "Stack samples captured from registered controller threads by the "
    "sampling profiler",
)
PROFILER_OVERHEAD = REGISTRY.gauge(
    "nos_tpu_profiler_overhead_fraction",
    "Sampler duty cycle: time spent capturing stacks divided by wall "
    "time enabled (the profiler's measured overhead budget)",
)

# Placement forecasting (nos_tpu/forecast/): earliest-feasible-start
# ETAs, backfill-safety verdicts, and the calibration that gates letting
# forecasts actuate (ROADMAP item 2).
GANG_ETA_SECONDS = REGISTRY.histogram(
    "nos_tpu_gang_eta_seconds",
    "Forecast earliest-feasible-start ETA per pending gang "
    "(by stage=feasible-now|recarve|blocked; blocked gangs without "
    "expected-completion hints publish no ETA)",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 15.0, 30.0, 60.0),
)
FORECAST_ACCURACY_RATIO = REGISTRY.gauge(
    "nos_tpu_forecast_accuracy_ratio",
    "Rolling forecast calibration: absolute ETA error divided by the "
    "gang's actual arrival-to-bound wait, joined at gang-bound "
    "(by quantile=p50|p95 over the calibration window)",
)
BACKFILL_UNSAFE_TOTAL = REGISTRY.counter(
    "nos_tpu_backfill_unsafe_total",
    "Backfill-safety shadow trials that found a (small pod, node) "
    "placement which would delay the oldest pending gang's ETA",
)
FORECAST_RUNS = REGISTRY.counter(
    "nos_tpu_forecast_runs_total",
    "Completed forecast cycles (background thread or on-demand "
    "/debug/forecast?refresh=1)",
)

# Health timeline (nos_tpu/timeline/): longitudinal sampling of the
# registry + process vitals + structure sizes into a bounded ring, and
# the leak/stall/regression detector verdicts computed over it.
TIMELINE_SAMPLES = REGISTRY.counter(
    "nos_tpu_timeline_samples_total",
    "Samples appended to the timeline ring (one per sampler interval)",
)
TIMELINE_SERIES = REGISTRY.gauge(
    "nos_tpu_timeline_series",
    "Distinct series present in the most recent timeline sample",
)
TIMELINE_FINDINGS = REGISTRY.counter(
    "nos_tpu_timeline_findings_total",
    "New detector findings over the timeline ring "
    "(by detector=stall|leak|regression, series); hysteresis means an "
    "active finding counts once, not once per tick",
)
TIMELINE_SAMPLE_DURATION = REGISTRY.histogram(
    "nos_tpu_timeline_sample_duration_seconds",
    "Wall time one timeline sample (all collectors + ring append) costs "
    "— the numerator of the <=2% sampling-overhead budget",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1),
)
METRIC_SERIES_DROPPED = REGISTRY.counter(
    "nos_tpu_metric_series_dropped_total",
    "Distinct label sets refused by a family's series budget and folded "
    "into its _other child (by family)",
)
CAPACITY_POOL_CHIPS = REGISTRY.gauge(
    "nos_tpu_capacity_pool_chips",
    "Exact per-pool chip rollups (by pool, state=total|used|free) — the "
    "tier the cardinality governor keeps full-fidelity when per-node "
    "series are over budget; series are removed when the pool vanishes",
)
TRACE_RETAINED = REGISTRY.counter(
    "nos_tpu_trace_retained_total",
    "Traces pinned into the tail-kept reservoir "
    "(by verdict=error|unschedulable|slow)",
)

# ---------------------------------------------------------------------------
# Label-reset audit (enforced by tests/util/test_lint.py): every family
# carrying a node=/pool=/model= label either registers the code path that
# deletes its series when the labeled object goes away, or carries a
# written justification for living without one. Stale entries (family no
# longer labeled that way, or labeled families missing here) fail the lint.
LABEL_RESET_PATHS: Dict[str, str] = {
    "nos_tpu_capacity_node_chips": "CapacityLedger._drop_node_gauges on node delete",
    "nos_tpu_node_fragmentation_index": "CapacityLedger._drop_node_gauges on node delete",
    "nos_tpu_capacity_pool_chips": "CapacityLedger._export_gauges removes vanished pools",
    "nos_tpu_autoscaler_replicas": "Autoscaler._collect_orphans removes series on ModelServing delete",
}
LABEL_RESET_EXEMPT: Dict[str, str] = {
    "nos_tpu_plan_pool_duration_seconds": (
        "histogram of completed plan durations keyed by the operator's "
        "static pool set (bounded by config, not by cluster objects); "
        "history must survive pool reconfiguration for trend comparison"
    ),
    "nos_tpu_serve_goodput_requests_total": (
        "monotonic per-model counters; deleting on model teardown would "
        "erase goodput history mid-scrape and break rate() — bounded by "
        "the deployed-model set and governable via seriesBudget"
    ),
    "nos_tpu_serve_goodput_tokens_total": (
        "same as nos_tpu_serve_goodput_requests_total — monotonic "
        "goodput history outlives the model object by design"
    ),
}
