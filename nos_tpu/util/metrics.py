"""Domain metrics: Prometheus-text-format registry.

The reference exposes only controller-runtime's default metrics and has no
domain counters — called out as a gap in SURVEY.md §5 ("no 'slices
created' counter") that the TPU build should fill. This registry backs the
north-star measurements: plans applied, slices created/deleted, pods
scheduled, schedule latency, preemptions, gang completions.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple


class Counter:
    def __init__(self, name: str, help_text: str) -> None:
        self.name = name
        self.help = help_text
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} counter\n"
            f"{self.name} {self.value}\n"
        )


class Gauge(Counter):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def render(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} gauge\n"
            f"{self.name} {self.value}\n"
        )


class Histogram:
    DEFAULT_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

    # Percentiles are computed from a bounded window of recent observations
    # so a long-running scheduler never grows memory; counts/sum/buckets
    # stay exact forever.
    WINDOW = 1024

    def __init__(self, name: str, help_text: str, buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        from collections import deque

        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._recent = deque(maxlen=self.WINDOW)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._count += 1
            self._recent.append(value)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, p: float) -> Optional[float]:
        with self._lock:
            if not self._recent:
                return None
            ordered = sorted(self._recent)
            index = min(len(ordered) - 1, int(p / 100.0 * len(ordered)))
            return ordered[index]

    def render(self) -> str:
        with self._lock:
            lines = [
                f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} histogram",
            ]
            cumulative = 0
            for bound, count in zip(self.buckets, self._counts):
                cumulative += count
                lines.append(f'{self.name}_bucket{{le="{bound}"}} {cumulative}')
            cumulative += self._counts[-1]
            lines.append(f'{self.name}_bucket{{le="+Inf"}} {cumulative}')
            lines.append(f"{self.name}_sum {self._sum}")
            lines.append(f"{self.name}_count {self._count}")
            return "\n".join(lines) + "\n"


class MetricsRegistry:
    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help_text))

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help_text))

    def histogram(self, name: str, help_text: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help_text, buckets))

    def _get_or_create(self, name: str, factory):
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = factory()
            return self._metrics[name]

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "".join(m.render() for m in sorted(metrics, key=lambda m: m.name))

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            metrics = dict(self._metrics)
        out: Dict[str, float] = {}
        for name, metric in metrics.items():
            if isinstance(metric, Histogram):
                out[f"{name}_count"] = metric.count
                p50 = metric.percentile(50)
                if p50 is not None:
                    out[f"{name}_p50"] = p50
            else:
                out[name] = metric.value
        return out


# The process-wide registry (controller-runtime's metrics.Registry analogue).
REGISTRY = MetricsRegistry()

PLANS_APPLIED = REGISTRY.counter(
    "nos_tpu_partitioning_plans_applied_total", "Partitioning plans actuated"
)
DIVERGENCE_REPLANS = REGISTRY.counter(
    "nos_tpu_partitioning_divergence_replans_total",
    "Immediate replans triggered by actuation diverging from spec",
)
BOARD_RESERVATIONS = REGISTRY.counter(
    "nos_tpu_board_reservations_total",
    "Nodes reserved to drain for full-board pods",
)
SLICES_CREATED = REGISTRY.counter(
    "nos_tpu_slices_created_total", "TPU slices carved by agents"
)
SLICES_DELETED = REGISTRY.counter(
    "nos_tpu_slices_deleted_total", "TPU slices destroyed by agents"
)
PODS_SCHEDULED = REGISTRY.counter(
    "nos_tpu_pods_scheduled_total", "Pods bound by the scheduler"
)
PREEMPTIONS = REGISTRY.counter(
    "nos_tpu_preemptions_total", "Pods evicted by quota preemption"
)
GANGS_SCHEDULED = REGISTRY.counter(
    "nos_tpu_gangs_scheduled_total", "Gangs released for binding"
)
SCHEDULE_LATENCY = REGISTRY.histogram(
    "nos_tpu_schedule_latency_seconds", "Per-pod scheduling cycle latency"
)

# Partitioner planning loop (the nos_scheduling_latency north star). The
# fork/revert/commit counters plus the nodes-copied gauge make the CoW
# snapshot's touched-node economics visible in scraped metrics: nodes
# copied per fork should hover near 1 regardless of cluster size, and a
# regression back toward O(cluster) copying shows up immediately.
PLAN_DURATION = REGISTRY.histogram(
    "nos_tpu_plan_duration_seconds",
    "Planner.plan() wall time per invocation",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
)
SNAPSHOT_FORKS = REGISTRY.counter(
    "nos_tpu_snapshot_forks_total", "Snapshot forks started by the planner"
)
SNAPSHOT_COMMITS = REGISTRY.counter(
    "nos_tpu_snapshot_commits_total", "Snapshot forks committed (trial kept)"
)
SNAPSHOT_REVERTS = REGISTRY.counter(
    "nos_tpu_snapshot_reverts_total", "Snapshot forks reverted (trial discarded)"
)
SNAPSHOT_NODES_COPIED = REGISTRY.counter(
    "nos_tpu_snapshot_nodes_copied_total",
    "SnapshotNodes cloned into fork journals (CoW touched-node copies)",
)
FORK_NODES_COPIED = REGISTRY.gauge(
    "nos_tpu_snapshot_fork_nodes_copied",
    "Nodes cloned by the most recently ended fork (commit or revert)",
)
MULTIHOST_EXPANSIONS = REGISTRY.counter(
    "nos_tpu_multihost_expansions_total",
    "Oversized chip requests expanded into multi-host slice gangs",
)
WEBHOOK_DENIALS = REGISTRY.counter(
    "nos_tpu_webhook_denials_total",
    "AdmissionReview requests the validating webhooks denied",
)
LEADER_TRANSITIONS = REGISTRY.counter(
    "nos_tpu_leader_transitions_total",
    "Leadership acquisitions across all components' leases",
)

# Serving engine (a replica exports these next to the control-plane set).
SERVE_REQUESTS = REGISTRY.counter(
    "nos_tpu_serve_requests_total", "Requests completed by the serving engine"
)
SERVE_TOKENS = REGISTRY.counter(
    "nos_tpu_serve_tokens_total", "Tokens generated by the serving engine"
)
SERVE_TICKS = REGISTRY.counter(
    "nos_tpu_serve_decode_ticks_total",
    "Batched decode ticks executed (each reads the weights once)",
)
SERVE_SLOT_TICKS_ACTIVE = REGISTRY.counter(
    "nos_tpu_serve_slot_ticks_active_total",
    "Per-slot ticks spent on live requests (active / (ticks*slots) = "
    "batch occupancy)",
)
SERVE_PREFIX_HITS = REGISTRY.counter(
    "nos_tpu_serve_prefix_cache_hits_total",
    "Chunked admissions that reused a cached prompt-prefix K/V",
)
SERVE_PREFIX_TOKENS_REUSED = REGISTRY.counter(
    "nos_tpu_serve_prefix_tokens_reused_total",
    "Prompt tokens whose prefill was skipped via the prefix cache",
)
SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "nos_tpu_serve_queue_depth", "Requests waiting for a free slot"
)
SERVE_SLOTS = REGISTRY.gauge(
    "nos_tpu_serve_slots", "Configured slot count (the occupancy denominator)"
)
