"""Watch event predicates (reference pkg/util/predicate/predicates.go)."""
from __future__ import annotations

from nos_tpu.kube.store import DELETED, WatchEvent


def matching_name(name: str):
    def predicate(event: WatchEvent) -> bool:
        return event.object.metadata.name == name

    return predicate


def exclude_delete(event: WatchEvent) -> bool:
    return event.type != DELETED


def annotations_changed_or_added(event: WatchEvent) -> bool:
    """Coarse stand-in for AnnotationsChangedPredicate: our store events do
    not carry the old object, so any ADDED/MODIFIED passes; reconcilers are
    level-triggered and tolerate spurious wakeups."""
    return event.type != DELETED


def and_(*predicates):
    def predicate(event: WatchEvent) -> bool:
        return all(p(event) for p in predicates)

    return predicate
