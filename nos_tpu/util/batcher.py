"""Batcher: dual-timer event coalescing.

Reference pkg/util/batcher.go:25-130: items accumulate in a batch that is
released when either the *timeout window* (max total wait, started at the
first Add) or the *idle window* (quiet period since the last Add) elapses.
Used to coalesce pending-pod events so the planner runs once per burst
(helm defaults: timeout 60s, idle 10s — values.yaml:278-285).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Generic, List, TypeVar

T = TypeVar("T")


class Batcher(Generic[T]):
    def __init__(self, timeout_seconds: float, idle_seconds: float = 0.0) -> None:
        self.timeout = timeout_seconds
        self.idle = idle_seconds
        self._lock = threading.Lock()
        self._batch: List[T] = []
        self._first_add: float = 0.0
        self._last_add: float = 0.0
        self._ready: "queue.Queue[List[T]]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ inputs

    def add(self, item: T) -> None:
        with self._lock:
            now = time.monotonic()
            if not self._batch:
                self._first_add = now
            self._last_add = now
            self._batch.append(item)

    def current_batch_size(self) -> int:
        with self._lock:
            return len(self._batch)

    # ----------------------------------------------------------- outputs

    def ready(self, timeout: float | None = None) -> "List[T] | None":
        """Block until a batch is released; None on timeout/stop."""
        try:
            return self._ready.get(timeout=timeout)
        except queue.Empty:
            return None

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="batcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        tick = min(0.01, max(self.timeout / 100.0, 0.001))
        while not self._stop.is_set():
            time.sleep(tick)
            released: "List[T] | None" = None
            with self._lock:
                if not self._batch:
                    continue
                now = time.monotonic()
                timed_out = now - self._first_add >= self.timeout
                idle = self.idle > 0 and now - self._last_add >= self.idle
                if timed_out or idle:
                    released = self._batch
                    self._batch = []
            if released:
                self._ready.put(released)
