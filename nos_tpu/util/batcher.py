"""Batcher: dual-timer event coalescing.

Reference pkg/util/batcher.go:25-130: items accumulate in a batch that is
released when either the *timeout window* (max total wait, started at the
first Add) or the *idle window* (quiet period since the last Add) elapses.
Used to coalesce pending-pod events so the planner runs once per burst
(helm defaults: timeout 60s, idle 10s — values.yaml:278-285).
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Generic, List, TypeVar

T = TypeVar("T")


class Batcher(Generic[T]):
    def __init__(self, timeout_seconds: float, idle_seconds: float = 0.0) -> None:
        self.timeout = timeout_seconds
        self.idle = idle_seconds
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._batch: List[T] = []
        self._first_add: float = 0.0
        self._last_add: float = 0.0
        self._ready: "queue.Queue[List[T]]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ inputs

    def add(self, item: T) -> None:
        with self._lock:
            now = time.monotonic()
            if not self._batch:
                self._first_add = now
            self._last_add = now
            self._batch.append(item)
            self._cond.notify()

    def current_batch_size(self) -> int:
        with self._lock:
            return len(self._batch)

    def fire_now(self) -> None:
        """Release the current batch immediately, bypassing both windows.

        Used for feedback events that must not wait out a batch window —
        e.g. a node reporting that actuation diverged from spec. An empty
        release is delivered too: consumers that treat the batch as a
        trigger (re-fetching work themselves) still get woken."""
        with self._lock:
            released = self._batch
            self._batch = []
        self._ready.put(released)

    # ----------------------------------------------------------- outputs

    def ready(self, timeout: float | None = None) -> "List[T] | None":
        """Block until a batch is released; None on timeout/stop."""
        try:
            return self._ready.get(timeout=timeout)
        except queue.Empty:
            return None

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, name="batcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        # Condition-driven: sleep until the earliest window deadline (or
        # until an add() arrives into an empty batch). A fixed-tick poll
        # here burned a quarter of the control plane's CPU on small hosts.
        while not self._stop.is_set():
            released: "List[T] | None" = None
            with self._lock:
                if not self._batch:
                    self._cond.wait(timeout=0.2)
                    continue
                now = time.monotonic()
                deadline = self._first_add + self.timeout
                if self.idle > 0:
                    deadline = min(deadline, self._last_add + self.idle)
                if now >= deadline:
                    released = self._batch
                    self._batch = []
                else:
                    self._cond.wait(timeout=deadline - now)
                    continue
            if released:
                self._ready.put(released)
