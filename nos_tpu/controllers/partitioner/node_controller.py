"""StateNodeController: keeps ClusterState in sync and initializes virgin
TPU nodes (reference internal/controllers/gpupartitioner/node_controller.go:60-135).
"""
from __future__ import annotations

import logging
from typing import Optional

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.labels import is_tpu_partitioning_enabled
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import ClusterState

log = logging.getLogger("nos_tpu.partitioner")


class StateNodeController:
    def __init__(
        self,
        store: KubeStore,
        cluster_state: ClusterState,
        initializer=None,
    ) -> None:
        self.store = store
        self.cluster_state = cluster_state
        self.initializer = initializer

    def reconcile(self, req: Request) -> Optional[Result]:
        node = self.store.try_get("Node", req.name)
        if node is None:
            self.cluster_state.delete_node(req.name)
            return None
        # First contact with a virgin TPU node: apply the fewest-slices
        # geometry so its resources become schedulable (node_controller.go:89-95).
        if (
            self.initializer is not None
            and is_tpu_partitioning_enabled(node)
            and not self.initializer.is_initialized(node)
        ):
            self.initializer.init_node_partitioning(node)
            node = self.store.get("Node", req.name)
        pods = [
            p
            for p in self.store.list_by_index(
                "Pod", constants.INDEX_POD_NODE, node.metadata.name
            )
            if p.status.phase in ("Pending", "Running")
        ]
        self.cluster_state.update_node(node, pods)
        return None
