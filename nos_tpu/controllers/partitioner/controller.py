"""PartitionerController: pending pods → batch → snapshot → plan → actuate.

Reference internal/controllers/gpupartitioner/partitioner_controller.go:81-239:
pods that re-partitioning could help are batched (Batcher, timeout/idle
windows); the batch is processed only when every managed node has reported
the last plan (the spec/status plan-id gate, :118-122 and :212-232 —
generalized here over all nodes of the mode, which also covers multi-host
slices spanning several nodes); processing takes a snapshot, plans, and
actuates the diff.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.labels import kind_matches
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.objects import Pod
from nos_tpu.kube.store import KubeStore
from nos_tpu.timeline.sizes import SIZES
from nos_tpu.timeline.watchdog import WATCHDOG
from nos_tpu.partitioning.core import (
    Actuator,
    ClusterState,
    PartitioningPlan,
    Planner,
)
from nos_tpu.util import metrics
from nos_tpu.util import pod as podutil
from nos_tpu.util.batcher import Batcher
from nos_tpu.util.loop_health import LOOPS, BusyMeter
from nos_tpu.util.profiling import PROFILER
from nos_tpu.util.tracing import TRACER

log = logging.getLogger("nos_tpu.partitioner")


class PartitionerController:
    def __init__(
        self,
        store: KubeStore,
        cluster_state: ClusterState,
        snapshot_taker,
        planner: Planner,
        actuator: Actuator,
        kind: str = "tpu",
        batch_timeout_seconds: float = 60.0,
        batch_idle_seconds: float = 10.0,
        plan_id_fn=lambda: str(int(time.time() * 1000)),
        tracked_resource_fn=None,
        scheduler_name: str = "",
        recorder=None,
        flight_recorder=None,
        auditor=None,
        incremental_planning: bool = True,
        incremental_dirty_threshold: Optional[float] = None,
        capacity_ledger=None,
        pool_sharding: bool = False,
        pool_parallelism: str = "serial",
        pool_max_workers: int = 0,
        pool_backend: str = "",
        pool_cycle_timeout_seconds: float = 5.0,
        warm_state_path: str = "",
        warm_state_save_interval_seconds: float = 30.0,
        forecaster=None,
    ) -> None:
        self.store = store
        # Optional kube/events.py EventRecorder: PartitioningApplied when a
        # plan actuates, CarveFailed (with the planner's lacking-profile
        # reason) per pod the plan could not serve.
        self.recorder = recorder
        # Optional record.FlightRecorder (planner.plan + actuation records)
        # and record.InvariantAuditor (sampled shadow-recompute of the
        # planner's incremental caches after a plan).
        self.flight_recorder = flight_recorder
        self.auditor = auditor
        # Optional capacity.CapacityLedger (cluster-wide, shared with the
        # scheduler): observed once per plan cycle with the planner's
        # unserved reasons, so idle time between cycles gets attributed.
        self.capacity_ledger = capacity_ledger
        # Optional forecast.PlacementForecaster: notified once per plan
        # cycle with the pending batch (off-path — the forecaster runs on
        # its own thread with its own snapshot maintainer and planner).
        self.forecaster = forecaster
        # namespaced_name -> last CarveFailed reason recorded; pruned to
        # the live pending set every cycle so deleted pods don't leak.
        self._last_carve_reason: Dict[str, str] = {}
        self.cluster_state = cluster_state
        self.snapshot_taker = snapshot_taker
        self.planner = planner
        self.actuator = actuator
        self.kind = kind
        # Non-empty: plan only for pods this scheduler profile will bind
        # (matches SchedulerConfig.scheduler_name); empty claims all.
        self.scheduler_name = scheduler_name
        self.batcher: Batcher[str] = Batcher(batch_timeout_seconds, batch_idle_seconds)
        self.plan_id_fn = plan_id_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.plans_applied = 0  # domain metric (gap noted in SURVEY.md §5)
        self.nodes_repartitioned = 0  # per-node slice reconfigs (north star)
        from nos_tpu.partitioning.core.snapshot import ClusterSnapshot

        # Which extended resources this mode's planning can serve (per-mode
        # SliceFilter analogue); defaults to the tpu mode's slice resources.
        self.tracked_resource_fn = tracked_resource_fn or ClusterSnapshot.is_tracked_resource
        # Divergence memo: node name -> spec plan id already replanned for,
        # so one infeasible plan triggers exactly one immediate replan.
        self._diverged: dict = {}
        # Incremental planning: keep one base snapshot alive across cycles
        # and hand the planner a dirty set derived from store deltas
        # instead of rebuilding the world (see incremental.py). Off =
        # the legacy take-snapshot-per-cycle path, bit-identical to prior
        # releases.
        self.incremental_planning = incremental_planning
        if incremental_dirty_threshold is not None:
            self.planner.incremental_dirty_threshold = incremental_dirty_threshold
        self._maintainer = None
        # Pool-sharded planning (pools.py): partition the cluster into
        # pools no gang/affinity/quota edge crosses, keep one incremental
        # base + one planner per pool, plan them independently, and merge
        # under cross-pool invariants. Requires incremental planning (the
        # per-pool bases ARE incremental snapshots).
        self.pool_sharding = pool_sharding and incremental_planning
        self.pool_parallelism = pool_parallelism
        self.pool_max_workers = pool_max_workers
        # Pool execution backend (procpool.py): empty = follow
        # pool_parallelism; "process" runs one long-lived worker process
        # per pool, fed dirty-node deltas, escalating to in-parent serial
        # planning (plus a pool rebuild) for any pool whose worker dies
        # or wedges past the cycle timeout.
        self.pool_backend = pool_backend
        self.pool_cycle_timeout_seconds = pool_cycle_timeout_seconds
        self._worker_pool = None
        # Why process planning can be refused at runtime: a framework
        # whose plugins fall outside procpool's distributable registry.
        self._process_disabled = False
        # Per-pool replica of the WORKER's post-plan base state: refreshed
        # with the same dirty deltas the worker gets, overlaid with the
        # touched nodes each plan reply ships. Reconstructing desired from
        # it (instead of from the parent's observed-only pool bases) keeps
        # carve retries alive when an actuation write is lost.
        self._pool_mirror: Dict[str, Dict] = {}
        # Parent-owned fairness ledger for process mode: worker-local
        # first-seen clocks would drift across processes and reset on
        # respawn, so the parent stamps ages and ships them explicitly.
        self._pending_ledger = None
        self._shard_maintainer = None
        self._pool_planners: Dict[str, Planner] = {}
        # Warm-state persistence (snapcodec.py): after each plan cycle the
        # planners' futility/verdict memos are saved keyed by node-state
        # signature; a restart or full-rebuild fallback adopts the entries
        # whose signatures still match instead of replaying the world.
        self._warm_codec = None
        if warm_state_path and incremental_planning:
            from nos_tpu.partitioning.core.snapcodec import WarmStateCodec

            self._warm_codec = WarmStateCodec(
                warm_state_path,
                save_interval_seconds=warm_state_save_interval_seconds,
            )
        # Base-object identity from the previous cycle, so the unsharded
        # incremental path can detect a rebuild (fresh base) and warm-boot.
        self._last_base = None
        # Saturation telemetry: phase histogram children cached here
        # (labels() takes a registry lock — not for the hot loop) and a
        # busy meter for the batch loop itself.
        self._phase_refresh = metrics.PARTITIONER_PHASE.labels(kind=kind, phase="refresh")
        self._phase_plan = metrics.PARTITIONER_PHASE.labels(kind=kind, phase="plan")
        self._phase_actuate = metrics.PARTITIONER_PHASE.labels(kind=kind, phase="actuate")
        self._busy = BusyMeter(f"partitioner-{kind}")

    # ----------------------------------------------------- pod reconcile

    def reconcile(self, req: Request) -> Optional[Result]:
        pod = self.store.try_get("Pod", req.name, req.namespace)
        if pod is None:
            return None
        if not self._requests_tracked_resources(pod):
            log.debug("%s: no %s-tracked extra resources", req.name, self.kind)
            return None
        if not podutil.extra_resources_could_help_scheduling(pod):
            log.debug("%s: repartitioning cannot help (schedulable/preempting/bound)", pod.namespaced_name)
            return None
        if not self.cluster_state.is_partitioning_enabled(self.kind):
            # The pod's event can overtake the node event that enables
            # partitioning (real informers deliver kinds on independent
            # streams) — dropping here would orphan the pod forever. Requeue
            # with pod-age-proportional backoff: tight while the race window
            # is plausible, capped at 30s so a cluster that genuinely has no
            # nodes of this kind only pays a slow heartbeat per pod.
            age = max(0.0, time.time() - pod.metadata.creation_timestamp)
            delay = min(30.0, max(1.0, age / 4.0))
            log.debug(
                "%s: partitioning disabled for kind=%s, requeueing in %.1fs",
                pod.namespaced_name, self.kind, delay,
            )
            return Result(requeue_after=delay)
        # Nodes whose agents have not confirmed their current plan are
        # FROZEN in the snapshot (per-node generalization of the global
        # gate at partitioner_controller.go:118-122) — batching proceeds;
        # the planner simply cannot carve an in-flight node again.
        log.debug("%s: added to %s batch", pod.namespaced_name, self.kind)
        # First observation starts the pod's journey trace (observe→bind);
        # the scheduler and the batch processor parent their stages on it.
        root = TRACER.journey_root(
            ("pod", pod.namespaced_name),
            "pod.journey",
            pod=pod.namespaced_name,
            namespace=pod.metadata.namespace,
        )
        root.add_event("partitioner.observed", kind=self.kind)
        self.batcher.add(pod.namespaced_name)
        return None

    def _requests_tracked_resources(self, pod: Pod) -> bool:
        from nos_tpu.util import resources as res

        request = res.compute_pod_request(pod)
        return any(self.tracked_resource_fn(name) for name in request)

    # ------------------------------------------------- divergence watch

    def reconcile_node_divergence(self, req: Request) -> Optional[Result]:
        """Node annotation events: when an agent has acknowledged the
        current plan (handshake complete) but its reported geometry does
        not match spec — the actuator clamped an infeasible spec — replan
        IMMEDIATELY from the reported truth instead of waiting out the
        next pod batch window. Extends the reference's plan gate
        (partitioner_controller.go:118-122,212-232), which only knows
        "reported yet?", with "reported *what was asked*?"."""
        node = self.store.try_get("Node", req.name)
        if node is None:
            self._diverged.pop(req.name, None)
            return None
        if not kind_matches(node, self.kind):
            return None
        ann = node.metadata.annotations
        spec_plan = ann.get(annot.SPEC_PARTITIONING_PLAN)
        status_plan = ann.get(annot.STATUS_PARTITIONING_PLAN)
        if not spec_plan or spec_plan != status_plan:
            return None  # handshake in flight; the plan gate handles it
        spec, status = annot.parse_node_annotations(ann)
        if annot.spec_matches_status(spec, status):
            self._diverged.pop(req.name, None)
            return None
        if not self.fetch_pending_pods():
            # No demand to replan FOR — and the batch processor is a no-op
            # with an empty pending set, so firing the batcher would leave
            # the infeasible spec in place forever (the agent keeps
            # re-clamping it, the handshake stays "acked but diverged").
            # With nothing asking for a different shape, the declarative
            # intent adopts reported reality: spec := status geometry under
            # the same plan id, which the agent then acks as an empty plan.
            # Not memo-gated: adoption is idempotent, and the memo may
            # already be burned by a replan that never touched this node.
            patch: dict = annot.strip_spec_annotations(ann)
            patch.update(
                annot.spec_from_geometries(annot.status_geometries(status))
            )
            metrics.DIVERGENCE_REPLANS.inc()
            log.info(
                "partitioner: %s reports geometry diverging from plan %s "
                "with no pending pods; spec adopts reported geometry",
                req.name,
                spec_plan,
            )
            self.store.patch_annotations("Node", req.name, "", patch)
            return None
        if self._diverged.get(req.name) == spec_plan:
            # Already replanned once for this stale plan. Keep the node on
            # a heartbeat: if the replan never reshaped it and the pending
            # set later drains, the adopt path above must still get a turn
            # (pods draining emits no Node event to wake this watch).
            return Result(requeue_after=1.0)
        self._diverged[req.name] = spec_plan
        metrics.DIVERGENCE_REPLANS.inc()
        log.info(
            "partitioner: %s reports geometry diverging from plan %s "
            "(spec clamped as infeasible); replanning now",
            req.name,
            spec_plan,
        )
        self.batcher.fire_now()
        return Result(requeue_after=1.0)

    # --------------------------------------------- capacity-freed watch

    def reconcile_capacity_freed(self, req: Request) -> Optional[Result]:
        """A pod that consumed tracked capacity reached a terminal phase
        (or was deleted): if pods are still pending, replan NOW instead of
        waiting out the batch window — freed chips idling for a window
        length on every job transition is the single largest utilization
        tax in a steady stream of short jobs."""
        for pod in self.fetch_pending_pods():
            if podutil.extra_resources_could_help_scheduling(
                pod
            ) and self._requests_tracked_resources(pod):
                log.debug(
                    "partitioner: capacity freed by %s with %s pending; "
                    "firing batch now",
                    req.namespaced_name,
                    pod.namespaced_name,
                )
                self.batcher.fire_now()
                return None
        return None

    # ------------------------------------------------------ batch loop

    def start(self) -> None:
        self.batcher.start()
        LOOPS.register(f"partitioner-{self.kind}", self._loop_stats)
        # Event-driven loop (batch windows only open when work arrives),
        # so periodic=False: idleness is legal and the watchdog only
        # stall-checks it when a harness arms it explicitly. The memo
        # structures register for the leak detector — they are pruned by
        # version key every cycle, and retention past pruning is exactly
        # the cross-cycle aging bug ROADMAP item 5 names.
        WATCHDOG.register(
            f"partitioner-{self.kind}",
            periodic=False,
            thread_name=f"partitioner-{self.kind}",
            counter_fn=lambda: self.plans_applied,
        )
        SIZES.register(
            f"planner.{self.kind}.verdict_cache",
            lambda: len(self.planner._verdict_cache.entries),
        )
        SIZES.register(
            f"planner.{self.kind}.futility_memo",
            lambda: len(self.planner._futility_cache),
        )
        self._thread = threading.Thread(
            target=self._batch_loop, name=f"partitioner-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.batcher.stop()
        LOOPS.unregister(f"partitioner-{self.kind}")
        WATCHDOG.unregister(f"partitioner-{self.kind}")
        SIZES.unregister(f"planner.{self.kind}.verdict_cache")
        SIZES.unregister(f"planner.{self.kind}.futility_memo")
        if self._worker_pool is not None:
            self._worker_pool.close()
            self._worker_pool = None
        if self._thread:
            self._thread.join(timeout=2.0)

    def _loop_stats(self) -> dict:
        stats = self._busy.snapshot()
        stats["plans_applied"] = self.plans_applied
        stats["nodes_repartitioned"] = self.nodes_repartitioned
        return stats

    def _batch_loop(self) -> None:
        PROFILER.register_thread()
        try:
            self._batch_loop_inner()
        finally:
            PROFILER.unregister_thread()

    def _batch_loop_inner(self) -> None:
        while not self._stop.is_set():
            t0 = time.monotonic()
            batch = self.batcher.ready(timeout=0.2)
            t1 = time.monotonic()
            WATCHDOG.beat(f"partitioner-{self.kind}")
            if batch is None:
                self._busy.record(0.0, idle_s=t1 - t0)
                continue
            try:
                self.process_pending_pods()
                # Level-triggered retry: a pod whose first plan attempt
                # could not help emits no further events (the scheduler
                # marks it unschedulable once), so capacity freed later —
                # e.g. other pods finishing — would never retrigger
                # planning. Re-enqueue whatever is still pending; the
                # batch windows pace the retry cadence.
                if self.cluster_state.is_partitioning_enabled(self.kind):
                    for pod in self.fetch_pending_pods():
                        if podutil.extra_resources_could_help_scheduling(
                            pod
                        ) and self._requests_tracked_resources(pod):
                            self.batcher.add(pod.namespaced_name)
            except Exception:  # pragma: no cover - defensive
                log.exception("partitioner batch processing failed")
            finally:
                self._busy.record(time.monotonic() - t1, idle_s=t1 - t0)

    # ------------------------------------------------------- processing

    def fetch_pending_pods(self) -> List[Pod]:
        """All pending unbound pods OUR scheduler can bind (reference
        :202-210 via field indexers).

        Pods with a foreign spec.schedulerName are excluded: the named
        scheduler never binds them, so planning for them would let them
        age without bound in the fairness sort and capture carved slices
        they can never use. The stronger unschedulable-only gate the
        batcher uses cannot be applied here — gang members waiting in
        Permit carry no Unschedulable condition, and dropping them from
        the candidates would deadlock a half-formed gang's remaining
        carves."""
        # copy=False: planning only reads the pods, and stable object
        # identity across cycles is what lets the planner's id-keyed pod
        # memos survive an incremental replan (the store replaces objects
        # on write, so a changed pod is a new object — a fresh memo key).
        return [
            p
            for p in self.store.list_by_index(
                "Pod", constants.INDEX_POD_PHASE, "Pending", copy=False
            )
            if not p.spec.node_name
            and (
                not self.scheduler_name
                or p.spec.scheduler_name == self.scheduler_name
            )
        ]

    def process_pending_pods(self) -> int:
        """Returns the number of nodes re-partitioned (0 = no-op plan)."""
        pending = self.fetch_pending_pods()
        if not pending:
            return 0
        # One batch serves N pods but a span belongs to one trace: the
        # processing stages are parented on the FIRST pending pod's journey
        # (batch-mates still correlate through the shared plan id
        # attribute on their own scheduler cycles).
        journey = TRACER.journey(("pod", pending[0].namespaced_name))
        # Watermark BEFORE the snapshot read: replay applies deltas up to
        # here, so the replayed snapshot sees exactly the state this plan
        # planned from (the plan's own actuation writes come after).
        revision = self.store.revision
        with TRACER.attach(journey):
            with TRACER.span(
                "partitioner.process", kind=self.kind, pending=len(pending)
            ) as proc:
                # Snapshot from the live store: pending pods come from the
                # store, so bindings/usage must too, or the plan races
                # fresh binds. Incrementally: drain store deltas into a
                # dirty set and refresh only those nodes of the persistent
                # base (the maintainer reads the live store too, after the
                # same revision watermark — same race profile for replay).
                shard = None
                with TRACER.span("snapshot.take"):
                    if self.pool_sharding:
                        t_snap = time.monotonic()
                        shard = self._shard_snapshot(pending)
                        snapshot = shard[0]
                        self._phase_refresh.observe(time.monotonic() - t_snap)
                        dirty = None
                    elif self.incremental_planning:
                        snapshot, dirty = self._maintain_snapshot()
                        if (
                            self._warm_codec is not None
                            and snapshot is not self._last_base
                        ):
                            # Fresh base object = cold start or rebuild
                            # fallback: adopt persisted memos for every
                            # node whose state signature still matches,
                            # and plan only the rest as dirty.
                            report = self._warm_codec.adopt(
                                snapshot, self.planner
                            )
                            dirty = set(report.unmatched)
                            self._publish_warm_boot(report)
                        self._last_base = snapshot
                    else:
                        t_snap = time.monotonic()
                        snapshot = self.snapshot_taker.take_snapshot(
                            self.cluster_state, store=self.store
                        )
                        self._phase_refresh.observe(time.monotonic() - t_snap)
                        dirty = None
                t_plan = time.monotonic()
                if shard is not None:
                    # The actuation baseline comes from the POOL bases,
                    # not the global one: plan() commits carves into its
                    # base, so the pool bases carry planned-but-not-yet-
                    # observed geometry the way the unsharded base does —
                    # diffing desired against the global (observed) state
                    # would re-actuate every un-acked node each cycle.
                    desired, current, unserved, pending_ages, audit_runs = (
                        self._plan_sharded(pending, shard)
                    )
                    if desired is None:
                        # Merge invariants failed: discard the cycle's
                        # plan (actuate a no-op), rebuild pools next
                        # cycle. The conflict counter + log already fired.
                        desired = current
                else:
                    current = snapshot.partitioning_state()
                    desired = self.planner.plan(snapshot, pending, dirty=dirty)
                    unserved = dict(
                        getattr(self.planner, "last_unserved", {}) or {}
                    )
                    pending_ages = dict(
                        getattr(self.planner, "last_pending_ages", {}) or {}
                    )
                    audit_runs = None
                self._phase_plan.observe(time.monotonic() - t_plan)
                plan = PartitioningPlan(desired_state=desired, id=self.plan_id_fn())
                proc.set_attributes(plan_id=plan.id)
                with TRACER.span("partitioner.actuate", plan_id=plan.id):
                    t_act = time.monotonic()
                    applied = self.actuator.apply(current, plan)
                    self._phase_actuate.observe(time.monotonic() - t_act)
                proc.set_attributes(nodes_repartitioned=applied)
                self._record_plan(
                    revision, pending, plan, applied, journey,
                    unserved=unserved, pending_ages=pending_ages,
                )
                if self.capacity_ledger is not None:
                    # One ledger observation per plan cycle: close the
                    # interval since the previous cycle and re-label the
                    # pending-idle bucket from this plan's carve failures.
                    self.capacity_ledger.observe(
                        time.time(),
                        unserved=dict(unserved),
                        trace_id=journey.trace_id if journey is not None else "",
                    )
                if self.forecaster is not None:
                    # Stash-and-wake only: the forecast itself runs on the
                    # forecaster's thread (its forecast.cycle span parents
                    # on this journey when it is still open).
                    self.forecaster.notify_cycle(
                        pending,
                        now=time.time(),
                        trace_id=(
                            journey.trace_id if journey is not None else ""
                        ),
                        journey=journey,
                    )
                if self.auditor is not None and self.auditor.should_audit():
                    if audit_runs is not None:
                        violations = self.auditor.audit_sharded_plan(
                            audit_runs,
                            snapshot=snapshot,
                            revision=revision,
                            ledger=self.capacity_ledger,
                        )
                    else:
                        violations = self.auditor.audit_plan(
                            self.planner,
                            snapshot,
                            revision=revision,
                            pending=pending,
                            desired=desired,
                            ledger=self.capacity_ledger,
                        )
                    proc.set_attributes(audit_violations=len(violations))
                self._save_warm_state(snapshot, shard)
        if applied:
            self.plans_applied += 1
            self.nodes_repartitioned += applied
            metrics.PLANS_APPLIED.inc()
            log.info(
                "partitioner: plan %s applied for %d pending pods", plan.id, len(pending)
            )
        self._record_plan_events(pending, applied, unserved=unserved)
        return applied

    def _maintain_snapshot(self):
        from nos_tpu.controllers.partitioner.incremental import (
            IncrementalSnapshotMaintainer,
        )

        if self._maintainer is None:
            self._maintainer = IncrementalSnapshotMaintainer(
                self.store, self.snapshot_taker, kind=self.kind
            )
        return self._maintainer.snapshot(self.cluster_state)

    # --------------------------------------------------- sharded planning

    def _shard_snapshot(self, pending: List[Pod]):
        from nos_tpu.controllers.partitioner.incremental import (
            PoolShardedMaintainer,
        )

        if self._shard_maintainer is None:
            self._shard_maintainer = PoolShardedMaintainer(
                self.store, self.snapshot_taker, kind=self.kind
            )
        return self._shard_maintainer.shard(self.cluster_state, pending)

    def _new_planner(self) -> Planner:
        """A pool planner with the controller planner's exact knobs —
        per-pool memo state, shared policy."""
        template = self.planner
        planner = Planner(
            template.framework,
            aging_chips_per_second=template.aging_chips_per_second,
            verdict_cache_enabled=template.verdict_cache_enabled,
            reuse_gang_trial=template.reuse_gang_trial,
            futility_memo_enabled=template.futility_memo_enabled,
            incremental_dirty_threshold=template.incremental_dirty_threshold,
        )
        return planner

    def _plan_sharded(self, pending: List[Pod], shard):
        """Plan every pool independently and merge. Returns
        ``(desired, current, unserved, pending_ages, audit_runs)`` where
        ``current`` is the merged pre-plan pool state (the actuation
        baseline); ``desired`` is None when the cross-pool merge
        invariants failed, in which case the caller actuates a no-op and
        the next cycle rebuilds."""
        from nos_tpu.partitioning.core.pools import (
            check_merge_invariants,
            merge_pool_states,
            node_capacities,
            run_pool_plans,
            split_pending,
        )

        snapshot, _dirty, partition, pool_snaps, pool_dirty = shard
        maintainer = self._shard_maintainer
        pool_pending = split_pending(pending, partition)
        if maintainer.last_rebuilt:
            # Fresh pool snapshots: fresh planners (the old ones' memos
            # are keyed to dead mutation clocks). Fairness first-seen
            # stamps carry over so pod aging survives the rebuild, and
            # persisted warm state shrinks the all-dirty sets to the
            # nodes whose observed state actually changed.
            old_planners = list(self._pool_planners.values()) or [self.planner]
            self._pool_planners = {}
            doc = None
            if self._warm_codec is not None:
                doc = self._warm_codec.load(
                    expected_codec=type(snapshot.codec).__name__
                )
            report_total = None
            for pool in partition.pools:
                planner = self._new_planner()
                for prior in old_planners:
                    planner.adopt_pending_seen(prior)
                if doc is not None:
                    pool_report = self._warm_codec.adopt(
                        pool_snaps[pool], planner, doc
                    )
                    pool_dirty[pool] = set(pool_report.unmatched)
                    if report_total is None:
                        from nos_tpu.partitioning.core.snapcodec import (
                            AdoptReport,
                        )

                        report_total = AdoptReport()
                    report_total.matched += pool_report.matched
                    report_total.unmatched |= pool_report.unmatched
                    report_total.adopted_entries += pool_report.adopted_entries
                self._pool_planners[pool] = planner
            if self._warm_codec is not None:
                from nos_tpu.partitioning.core.snapcodec import AdoptReport

                self._publish_warm_boot(report_total or AdoptReport(
                    unmatched=set(snapshot.get_nodes())
                ))
        metrics.PLAN_POOL_COUNT.labels(kind=self.kind).set(
            len(partition.pools)
        )
        backend = self._effective_backend()
        if backend == "process" and self._ensure_worker_pool(snapshot) is None:
            backend = self._effective_backend()
        if backend == "process":
            pool_desired, pool_current, unserved, pending_ages = (
                self._plan_pools_process(
                    snapshot,
                    partition,
                    pool_snaps,
                    pool_dirty,
                    pool_pending,
                    maintainer,
                )
            )
        else:
            metrics.PLAN_BACKEND.labels(backend=backend).inc(
                len(partition.pools)
            )

            def make_task(pool: str):
                def task():
                    planner = self._pool_planners[pool]
                    pool_snapshot = pool_snaps[pool]
                    # Pre-plan state FIRST: plan() commits successful
                    # carves into its base, so this is the last chance to
                    # read the pool's current geometry (merge-invariant
                    # and actuation baseline).
                    pool_current = pool_snapshot.partitioning_state()
                    t0 = time.monotonic()
                    desired = planner.plan(
                        pool_snapshot,
                        pool_pending[pool],
                        dirty=pool_dirty[pool],
                    )
                    duration = time.monotonic() - t0
                    return desired, pool_current, duration

                return task

            tasks = {pool: make_task(pool) for pool in partition.pools}
            outcomes = run_pool_plans(
                tasks, backend, self.pool_max_workers
            )
            pool_desired = {}
            pool_current = {}
            unserved = {}
            pending_ages = {}
            for pool, (desired, pool_cur, duration) in outcomes.items():
                pool_desired[pool] = desired
                pool_current[pool] = pool_cur
                metrics.PLAN_POOL_DURATION.labels(pool=pool).observe(duration)
                planner = self._pool_planners[pool]
                unserved.update(planner.last_unserved)
                pending_ages.update(planner.last_pending_ages)
        audit_runs = [
            (
                pool,
                self._pool_planners[pool],
                pool_snaps[pool],
                pool_pending[pool],
                pool_desired[pool],
            )
            for pool in partition.pools
        ]
        current = merge_pool_states(pool_current)
        violations = check_merge_invariants(
            partition,
            pool_current,
            pool_desired,
            capacities=node_capacities(pool_snaps.values()),
        )
        if violations:
            metrics.PLAN_MERGE_CONFLICTS.inc()
            maintainer.force_rebuild()
            log.error(
                "partitioner[%s]: sharded merge invariants failed, "
                "discarding plan and rebuilding pools: %s",
                self.kind,
                "; ".join(violations[:5]),
            )
            return None, current, unserved, pending_ages, audit_runs
        return (
            merge_pool_states(pool_desired),
            current,
            unserved,
            pending_ages,
            audit_runs,
        )

    # -------------------------------------------------- process backend

    def _effective_backend(self) -> str:
        """serial | thread | process — pool_backend wins when set, else
        pool_parallelism; a refused process backend (non-distributable
        framework) degrades to the thread/serial ladder."""
        backend = self.pool_backend or self.pool_parallelism
        if backend == "process" and self._process_disabled:
            return "thread" if self.pool_parallelism == "thread" else "serial"
        return backend if backend in ("thread", "process") else "serial"

    def _ensure_worker_pool(self, snapshot):
        from nos_tpu.partitioning.core.procpool import (
            PoolWorkerPool,
            framework_spec,
            planner_knobs,
        )

        if self._worker_pool is not None:
            return self._worker_pool
        spec = framework_spec(self.planner.framework)
        if spec is None:
            self._process_disabled = True
            log.warning(
                "partitioner[%s]: framework has plugins outside the "
                "distributable registry; process pool backend disabled, "
                "falling back to %s",
                self.kind,
                self._effective_backend(),
            )
            return None
        self._worker_pool = PoolWorkerPool(
            kind=self.kind,
            slice_codec_name=type(snapshot.codec).__name__,
            spec=spec,
            knobs=planner_knobs(self.planner),
            cycle_timeout_seconds=self.pool_cycle_timeout_seconds,
            warm_state_path=(
                self._warm_codec.path if self._warm_codec is not None else ""
            ),
        )
        return self._worker_pool

    def _plan_pools_process(
        self, snapshot, partition, pool_snaps, pool_dirty, pool_pending, maintainer
    ):
        """One process-backend plan cycle: ship dirty deltas + pending +
        parent-stamped fairness ages to every pool's worker, collect plan
        replies under the cycle deadline, reconstruct each pool's desired
        state from the mirror + touched nodes, and escalate any
        unavailable pool to an in-parent plan plus a pool rebuild (the
        rebuild re-bootstraps every worker from one consistent wire
        image next cycle)."""
        from nos_tpu.kube.serde import pod_to_wire
        from nos_tpu.partitioning.core.partition_state import (
            partitioning_state_from_dict,
        )
        from nos_tpu.partitioning.core.procpool import (
            PendingSeenLedger,
            WorkerUnavailable,
            quotas_to_wire,
            snapshot_node_to_wire,
        )

        worker_pool = self._worker_pool
        if maintainer.last_rebuilt:
            # Pool shapes changed: every worker's base is keyed to a dead
            # partition — re-bootstrap all of them from the fresh pool
            # bases, and restart the mirrors from the same states.
            self._pool_mirror = {}
            worker_pool.sync_pools(partition.pools)
            quotas = quotas_to_wire(
                self.store.list("ElasticQuota"),
                self.store.list("CompositeElasticQuota"),
            )
            for pool in sorted(partition.pools):
                entries = [
                    snapshot_node_to_wire(snap_node)
                    for _, snap_node in sorted(
                        pool_snaps[pool].get_nodes().items()
                    )
                ]
                try:
                    worker_pool.bootstrap(pool, entries, quotas)
                except WorkerUnavailable:
                    pass  # surfaces again in plan_cycle; escalated below
        if self._pending_ledger is None:
            self._pending_ledger = PendingSeenLedger()
        all_pending = [
            pod for pool in sorted(pool_pending) for pod in pool_pending[pool]
        ]
        ages = self._pending_ledger.ages(all_pending)
        requests = {}
        for pool in partition.pools:
            nodes = pool_snaps[pool].get_nodes()
            # Freshly bootstrapped workers already hold this cycle's
            # refreshed state — deltas would be redundant re-sends.
            deltas = (
                []
                if maintainer.last_rebuilt
                else [
                    snapshot_node_to_wire(nodes[name])
                    for name in sorted(pool_dirty[pool])
                    if name in nodes
                ]
            )
            requests[pool] = {
                "deltas": deltas,
                "pending": [pod_to_wire(pod) for pod in pool_pending[pool]],
                "ages": {
                    pod.namespaced_name: ages[pod.namespaced_name]
                    for pod in pool_pending[pool]
                },
                # Quota edges never cross pools (partition_pools merges
                # on them), so out-of-pool usage is structurally zero
                # today; the seam stays live for future cross-pool quota.
                "external_usage": {},
            }
        replies = worker_pool.plan_cycle(requests)
        pool_desired = {}
        pool_current = {}
        unserved = {}
        pending_ages = {}
        for pool in sorted(partition.pools):
            # Pre-plan state FIRST (an escalated in-parent plan below
            # commits carves into this same base).
            current = pool_snaps[pool].partitioning_state()
            pool_current[pool] = current
            mirror = self._pool_mirror.get(pool)
            if mirror is None:
                mirror = dict(current)
            else:
                for name in pool_dirty[pool]:
                    if name in current:
                        mirror[name] = current[name]
            reply = replies.get(pool)
            proxy = self._pool_planners[pool]
            if isinstance(reply, dict):
                mirror.update(partitioning_state_from_dict(reply["touched"]))
                self._pool_mirror[pool] = mirror
                pool_desired[pool] = dict(mirror)
                unserved.update(reply["unserved"])
                pending_ages.update(reply["pending_ages"])
                metrics.PLAN_POOL_DURATION.labels(pool=pool).observe(
                    reply["duration"]
                )
                metrics.PLAN_BACKEND.labels(backend="process").inc()
                # The proxy planner fronts for the worker in audit runs:
                # its (empty) memos satisfy the cache checks trivially,
                # and the shadow replan keys off these attributes.
                proxy.last_plan_mode = reply["plan_mode"]
                proxy.last_unserved = dict(reply["unserved"])
                proxy.last_pending_ages = dict(reply["pending_ages"])
            else:
                reason = (
                    reply.reason
                    if isinstance(reply, WorkerUnavailable)
                    else "no reply"
                )
                t0 = time.monotonic()
                desired = proxy.plan(
                    pool_snaps[pool],
                    pool_pending[pool],
                    pending_ages=dict(requests[pool]["ages"]),
                    dirty=pool_dirty[pool],
                )
                metrics.PLAN_POOL_DURATION.labels(pool=pool).observe(
                    time.monotonic() - t0
                )
                metrics.PLAN_BACKEND.labels(backend="escalated").inc()
                pool_desired[pool] = desired
                # The in-parent plan committed into the parent pool base,
                # which the (re)spawned worker's wire image cannot carry:
                # rebuild next cycle so mirror, worker, and parent resync
                # from one image.
                self._pool_mirror.pop(pool, None)
                unserved.update(proxy.last_unserved)
                pending_ages.update(proxy.last_pending_ages)
                maintainer.force_rebuild()
                if self.flight_recorder is not None:
                    self.flight_recorder.record_pool_escalation(
                        kind=self.kind,
                        pool=pool,
                        revision=self.store.revision,
                        reason=reason,
                    )
                log.warning(
                    "partitioner[%s]: pool %s escalated to in-parent "
                    "planning (%s); pools rebuild next cycle",
                    self.kind,
                    pool,
                    reason,
                )
        return pool_desired, pool_current, unserved, pending_ages

    # ------------------------------------------------------- warm state

    def _publish_warm_boot(self, report) -> None:
        if report.matched and not report.unmatched:
            outcome = "adopted"
        elif report.matched:
            outcome = "partial"
        else:
            outcome = "cold"
        metrics.WARM_BOOT_OUTCOME.labels(outcome=outcome).inc()
        log.info(
            "partitioner[%s]: warm boot %s (%d nodes matched, %d dirty, "
            "%d memo entries adopted)",
            self.kind,
            outcome,
            report.matched,
            len(report.unmatched),
            report.adopted_entries,
        )

    def _save_warm_state(self, snapshot, shard) -> None:
        if self._warm_codec is None:
            return
        if shard is None:
            self._warm_codec.save(snapshot, self.planner)
            return
        if not self._warm_codec.due():
            return
        # Sharded: every pool planner exports against its own pool base
        # (node keys are disjoint across pools), and the signatures are
        # taken from those SAME pool bases — the memos were derived from
        # their committed geometry, which the global (observed-only) base
        # may not have caught up with yet.
        _snapshot, _dirty, _partition, pool_snaps, _pool_dirty = shard
        if (
            self._effective_backend() == "process"
            and self._worker_pool is not None
        ):
            # The memos live in the workers; so do the node states they
            # were derived from — each worker exports its entries WITH
            # its own precomputed signatures (rate-limited by due()).
            entries = {}
            signatures: Dict[str, str] = {}
            for pool in sorted(self._worker_pool.pools()):
                exported = self._worker_pool.export_warm(pool)
                if exported is None:
                    continue
                pool_entries, pool_signatures = exported
                entries.update(pool_entries)
                signatures.update(pool_signatures)
            if signatures:
                self._warm_codec.save_entries(
                    snapshot, entries, signatures=signatures
                )
            return
        entries: Dict[str, dict] = {}
        signing_nodes: Dict[str, object] = {}
        for pool, planner in self._pool_planners.items():
            pool_snapshot = pool_snaps.get(pool)
            if pool_snapshot is not None:
                entries.update(planner.export_warm_state(pool_snapshot))
                signing_nodes.update(pool_snapshot.get_nodes())
        self._warm_codec.save_entries(snapshot, entries, nodes=signing_nodes)

    def _record_plan(
        self,
        revision: int,
        pending: List[Pod],
        plan,
        applied: int,
        journey,
        unserved: Optional[Dict[str, str]] = None,
        pending_ages: Optional[Dict[str, float]] = None,
    ) -> None:
        if self.flight_recorder is None:
            return
        from nos_tpu.partitioning.core.partition_state import (
            partitioning_state_to_dict,
        )

        self.flight_recorder.record_plan(
            kind=self.kind,
            revision=revision,
            pending=[p.namespaced_name for p in pending],
            pending_ages=dict(pending_ages or {}),
            plan_id=plan.id,
            desired=partitioning_state_to_dict(plan.desired_state),
            unserved=dict(unserved or {}),
            applied=applied,
            trace_id=journey.trace_id if journey is not None else "",
        )
        self.flight_recorder.record_actuation(
            kind=self.kind,
            plan_id=plan.id,
            revision=self.store.revision,
            applied=applied,
        )

    def _record_plan_events(
        self,
        pending: List[Pod],
        applied: int,
        unserved: Optional[Dict[str, str]] = None,
    ) -> None:
        """Event messages carry NO plan id: the id changes every cycle, so
        embedding it would defeat the recorder's dedup (a fresh Event
        object per plan) and the flood would drain the pod's rate-limit
        bucket — silently dropping the one PartitioningApplied that
        matters. The per-pod reason memo exists for the same budget: a
        plan loop re-deriving the identical verdict every few hundred ms
        records nothing until the verdict actually changes."""
        if self.recorder is None:
            return
        if unserved is None:
            unserved = getattr(self.planner, "last_unserved", {})
        live = {p.namespaced_name for p in pending}
        self._last_carve_reason = {
            k: v for k, v in self._last_carve_reason.items() if k in live
        }
        for pod in pending:
            reason = unserved.get(pod.namespaced_name)
            if reason is not None:
                if self._last_carve_reason.get(pod.namespaced_name) == reason:
                    continue
                self._last_carve_reason[pod.namespaced_name] = reason
                self.recorder.record(
                    pod,
                    constants.EVENT_REASON_CARVE_FAILED,
                    f"cannot carve slices for {pod.namespaced_name}: {reason}",
                    type="Warning",
                )
            else:
                self._last_carve_reason.pop(pod.namespaced_name, None)
                if applied:
                    self.recorder.record(
                        pod,
                        constants.EVENT_REASON_PARTITIONING_APPLIED,
                        f"re-partitioned {applied} node(s) to serve "
                        f"{pod.namespaced_name}",
                    )

    def idle(self) -> bool:
        return self.batcher.current_batch_size() == 0
