"""PartitionerController: pending pods → batch → snapshot → plan → actuate.

Reference internal/controllers/gpupartitioner/partitioner_controller.go:81-239:
pods that re-partitioning could help are batched (Batcher, timeout/idle
windows); the batch is processed only when every managed node has reported
the last plan (the spec/status plan-id gate, :118-122 and :212-232 —
generalized here over all nodes of the mode, which also covers multi-host
slices spanning several nodes); processing takes a snapshot, plans, and
actuates the diff.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.labels import kind_matches
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.objects import Pod
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import (
    Actuator,
    ClusterState,
    PartitioningPlan,
    Planner,
)
from nos_tpu.util import metrics
from nos_tpu.util import pod as podutil
from nos_tpu.util.batcher import Batcher

log = logging.getLogger("nos_tpu.partitioner")


class PartitionerController:
    def __init__(
        self,
        store: KubeStore,
        cluster_state: ClusterState,
        snapshot_taker,
        planner: Planner,
        actuator: Actuator,
        kind: str = "tpu",
        batch_timeout_seconds: float = 60.0,
        batch_idle_seconds: float = 10.0,
        plan_id_fn=lambda: str(int(time.time() * 1000)),
        tracked_resource_fn=None,
    ) -> None:
        self.store = store
        self.cluster_state = cluster_state
        self.snapshot_taker = snapshot_taker
        self.planner = planner
        self.actuator = actuator
        self.kind = kind
        self.batcher: Batcher[str] = Batcher(batch_timeout_seconds, batch_idle_seconds)
        self.plan_id_fn = plan_id_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.plans_applied = 0  # domain metric (gap noted in SURVEY.md §5)
        self.nodes_repartitioned = 0  # per-node slice reconfigs (north star)
        from nos_tpu.partitioning.core.snapshot import ClusterSnapshot

        # Which extended resources this mode's planning can serve (per-mode
        # SliceFilter analogue); defaults to the tpu mode's slice resources.
        self.tracked_resource_fn = tracked_resource_fn or ClusterSnapshot.is_tracked_resource

    # ----------------------------------------------------- pod reconcile

    def reconcile(self, req: Request) -> Optional[Result]:
        pod = self.store.try_get("Pod", req.name, req.namespace)
        if pod is None:
            return None
        if not self._requests_tracked_resources(pod):
            log.debug("%s: no %s-tracked extra resources", req.name, self.kind)
            return None
        if not podutil.extra_resources_could_help_scheduling(pod):
            log.debug("%s: repartitioning cannot help (schedulable/preempting/bound)", pod.namespaced_name)
            return None
        if not self.cluster_state.is_partitioning_enabled(self.kind):
            # The pod's event can overtake the node event that enables
            # partitioning (real informers deliver kinds on independent
            # streams) — dropping here would orphan the pod forever. Requeue
            # with pod-age-proportional backoff: tight while the race window
            # is plausible, capped at 30s so a cluster that genuinely has no
            # nodes of this kind only pays a slow heartbeat per pod.
            age = max(0.0, time.time() - pod.metadata.creation_timestamp)
            delay = min(30.0, max(1.0, age / 4.0))
            log.debug(
                "%s: partitioning disabled for kind=%s, requeueing in %.1fs",
                pod.namespaced_name, self.kind, delay,
            )
            return Result(requeue_after=delay)
        if self._waiting_for_nodes_to_report_plan():
            # Never plan on state the agents have not confirmed
            # (partitioner_controller.go:118-122).
            return Result(requeue_after=1.0)
        log.debug("%s: added to %s batch", pod.namespaced_name, self.kind)
        self.batcher.add(pod.namespaced_name)
        return None

    def _requests_tracked_resources(self, pod: Pod) -> bool:
        from nos_tpu.util import resources as res

        request = res.compute_pod_request(pod)
        return any(self.tracked_resource_fn(name) for name in request)

    # ------------------------------------------------------- plan gate

    def _waiting_for_nodes_to_report_plan(self) -> bool:
        for node in self.store.list("Node"):
            if not kind_matches(node, self.kind):
                continue
            spec_plan = node.metadata.annotations.get(annot.SPEC_PARTITIONING_PLAN)
            status_plan = node.metadata.annotations.get(annot.STATUS_PARTITIONING_PLAN)
            if spec_plan and spec_plan != status_plan:
                return True
        return False

    # ------------------------------------------------------ batch loop

    def start(self) -> None:
        self.batcher.start()
        self._thread = threading.Thread(
            target=self._batch_loop, name=f"partitioner-{self.kind}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.batcher.stop()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _batch_loop(self) -> None:
        while not self._stop.is_set():
            batch = self.batcher.ready(timeout=0.2)
            if batch is None:
                continue
            try:
                if self._waiting_for_nodes_to_report_plan():
                    # Re-add so the batch fires again once agents catch up.
                    time.sleep(0.1)
                    for item in batch:
                        self.batcher.add(item)
                    continue
                self.process_pending_pods()
                # Level-triggered retry: a pod whose first plan attempt
                # could not help emits no further events (the scheduler
                # marks it unschedulable once), so capacity freed later —
                # e.g. other pods finishing — would never retrigger
                # planning. Re-enqueue whatever is still pending; the
                # batch windows pace the retry cadence.
                if self.cluster_state.is_partitioning_enabled(self.kind):
                    for pod in self.fetch_pending_pods():
                        if podutil.extra_resources_could_help_scheduling(
                            pod
                        ) and self._requests_tracked_resources(pod):
                            self.batcher.add(pod.namespaced_name)
            except Exception:  # pragma: no cover - defensive
                log.exception("partitioner batch processing failed")

    # ------------------------------------------------------- processing

    def fetch_pending_pods(self) -> List[Pod]:
        """All pending unbound pods (reference :202-210 via field indexers)."""
        return [
            p
            for p in self.store.list_by_index("Pod", constants.INDEX_POD_PHASE, "Pending")
            if not p.spec.node_name
        ]

    def process_pending_pods(self) -> int:
        """Returns the number of nodes re-partitioned (0 = no-op plan)."""
        pending = self.fetch_pending_pods()
        if not pending:
            return 0
        snapshot = self.snapshot_taker.take_snapshot(self.cluster_state)
        current = snapshot.partitioning_state()
        desired = self.planner.plan(snapshot, pending)
        plan = PartitioningPlan(desired_state=desired, id=self.plan_id_fn())
        applied = self.actuator.apply(current, plan)
        if applied:
            self.plans_applied += 1
            self.nodes_repartitioned += applied
            metrics.PLANS_APPLIED.inc()
            log.info(
                "partitioner: plan %s applied for %d pending pods", plan.id, len(pending)
            )
        return applied

    def idle(self) -> bool:
        return self.batcher.current_batch_size() == 0
