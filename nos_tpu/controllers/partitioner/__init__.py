"""gpupartitioner-equivalent control plane (reference
internal/controllers/gpupartitioner/): the mode controller batching pending
pods into plan/actuate cycles, plus node/pod state controllers feeding
ClusterState.
"""

from nos_tpu.controllers.partitioner.controller import PartitionerController
from nos_tpu.controllers.partitioner.node_controller import StateNodeController
from nos_tpu.controllers.partitioner.pod_controller import StatePodController

__all__ = ["PartitionerController", "StateNodeController", "StatePodController"]
