"""Multi-host slice expansion: oversized chip requests become slice gangs.

BASELINE config #5's north-star flow: a user submits ONE pod asking
``google.com/tpu: 16`` on v5e. No single host can serve it — the chips
span an ICI domain of several hosts — so this controller (the mutating
half of the admission seam; the reference's operator owns the analogous
webhooks, /root/reference/cmd/operator/operator.go:96-117) expands it:

1. pick the smallest multi-host topology holding the request
   (``nos_tpu/tpu/known.py`` ``multihost_profile_for_chips`` — 16 chips on
   v5e → 4x4 over 2 hosts of 2x4);
2. rewrite the pod's request to its per-host share (one full-board slice)
   and label it a gang leader (``nos.nebuly.com/gang`` +
   ``gang-size=n_hosts`` + the multihost-topology annotation);
3. create the missing ``n_hosts - 1`` worker pods, owner-referenced to the
   leader, each requesting one board slice with the same gang labels.

Everything downstream then composes with no special cases: the tracker
sees n_hosts lacking board slices, the planner carves all hosts in ONE
plan (and its gang pre-pass refuses partial carves), the agents confirm
per-node plan ids (the plan gate's per-slice quorum), GangScheduling's
Permit binds the gang atomically inside one node pool, and gang-atomic
preemption frees every chip of the slice together.

Workers are garbage-collected when their leader disappears (the
owner-reference contract; this suite has no kube GC to lean on).

NOTE: in cluster-connected mode this rewrite must run as a mutating
admission webhook (pod specs are immutable post-admission on a real
apiserver); the in-process store models that seam.
"""
from __future__ import annotations

import copy
import logging
from typing import List, Optional

from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.objects import ObjectMeta, OwnerReference, Pod, PodPhase
from nos_tpu.kube.store import AlreadyExistsError, KubeStore, NotFoundError
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL
from nos_tpu.tpu.known import (
    KNOWN_ACCELERATORS,
    multihost_profile_for_chips,
    profile_for_chips,
)
from nos_tpu.util import metrics
from nos_tpu.util import resources as res

log = logging.getLogger("nos_tpu.multihost")

MULTIHOST_TOPOLOGY_ANNOTATION = "nos.nebuly.com/multihost-topology"
MULTIHOST_ROLE_LABEL = "nos.nebuly.com/multihost-role"
ROLE_LEADER = "leader"
ROLE_WORKER = "worker"


class MultihostExpander:
    def __init__(self, store: KubeStore) -> None:
        self.store = store

    # --------------------------------------------------------------- util

    def _cluster_accelerator(self) -> Optional[str]:
        """The accelerator generation of the partitioned TPU fleet.

        Heterogeneous fleets would carry the target generation on the pod
        (node selector); absent that, the first partitioned TPU node's
        label decides."""
        for node in self.store.list("Node"):
            accel = node.metadata.labels.get(labels.GKE_TPU_ACCELERATOR_LABEL)
            if accel and node.metadata.labels.get(labels.PARTITIONING_LABEL):
                return accel
        return None

    @staticmethod
    def _oversized_chips(pod: Pod, accelerator: str) -> int:
        """The plain-chip request when it exceeds one board, else 0."""
        request = res.compute_pod_request(pod)
        plain = int(request.get(constants.RESOURCE_TPU, 0))
        if plain <= 0:
            return 0
        if profile_for_chips(plain, accelerator) is not None:
            return 0  # single-host: normalized downstream, not expanded
        return plain

    # ---------------------------------------------------------- reconcile

    def reconcile(self, req: Request) -> Optional[Result]:
        pod = self.store.try_get("Pod", req.name, req.namespace)
        if pod is None:
            return None
        if pod.metadata.labels.get(MULTIHOST_ROLE_LABEL) == ROLE_WORKER:
            self._gc_orphan_worker(pod)
            return None
        if pod.metadata.labels.get(MULTIHOST_ROLE_LABEL) == ROLE_LEADER:
            self._ensure_workers(pod)
            return None
        if pod.status.phase != PodPhase.PENDING or pod.spec.node_name:
            return None
        accelerator = self._cluster_accelerator()
        if accelerator is None:
            return None
        chips = self._oversized_chips(pod, accelerator)
        if chips <= 0:
            return None
        profile = multihost_profile_for_chips(chips, accelerator)
        if profile is None:
            log.warning(
                "%s: %d chips exceed every multi-host topology of %s",
                pod.namespaced_name, chips, accelerator,
            )
            return None
        shape, n_hosts = profile
        self._expand(pod, accelerator, shape, n_hosts)
        return None

    # ------------------------------------------------------------- expand

    def _expand(self, pod: Pod, accelerator: str, shape: str, n_hosts: int) -> None:
        def mutate(p: Pod) -> None:
            expand_leader_in_place(p, accelerator, shape, n_hosts)

        self.store.patch_merge("Pod", pod.metadata.name, pod.metadata.namespace, mutate)
        leader = self.store.get("Pod", pod.metadata.name, pod.metadata.namespace)
        self._ensure_service(leader)
        self._ensure_workers(leader)
        metrics.MULTIHOST_EXPANSIONS.inc()
        log.info(
            "%s: expanded to %s multi-host slice — gang of %d hosts",
            pod.namespaced_name, shape, n_hosts,
        )

    def _ensure_service(self, leader: Pod) -> None:
        """Headless Service named after the gang: gives every member a
        stable DNS record (<hostname>.<gang>.<ns>.svc) so the coordinator
        address the env carries actually resolves."""
        from nos_tpu.kube.objects import Service, ServicePort, ServiceSpec
        from nos_tpu.parallel.distributed import DEFAULT_COORDINATOR_PORT

        gang = leader.metadata.labels.get(GANG_NAME_LABEL, "")
        if not gang or self.store.try_get("Service", gang, leader.metadata.namespace):
            return
        try:
            self.store.create(
                Service(
                    metadata=ObjectMeta(
                        name=gang,
                        namespace=leader.metadata.namespace,
                        owner_references=[
                            OwnerReference(
                                kind="Pod",
                                name=leader.metadata.name,
                                uid=leader.metadata.uid,
                                controller=True,
                            )
                        ],
                    ),
                    spec=ServiceSpec(
                        selector={GANG_NAME_LABEL: gang},
                        ports=[
                            ServicePort(
                                name="coordinator", port=DEFAULT_COORDINATOR_PORT
                            )
                        ],
                        cluster_ip="None",  # headless: per-pod DNS records
                    ),
                )
            )
        except AlreadyExistsError:
            pass

    def _ensure_workers(self, leader: Pod) -> None:
        """Idempotently create the leader's n_hosts-1 sibling workers.

        Over the API-backed store the worker is built from the leader's
        RAW wire object, so every field the projection doesn't model
        (volumes, probes, serviceAccount, …) carries over to the workers
        with full fidelity."""
        try:
            size = int(leader.metadata.labels.get(GANG_SIZE_LABEL, "0"))
        except ValueError:
            return
        raw_get = getattr(self.store, "raw_get", None)
        leader_wire = None
        if raw_get is not None:
            try:
                leader_wire = raw_get(
                    "Pod", leader.metadata.name, leader.metadata.namespace
                )
            except Exception:  # noqa: BLE001 — fall back to the projection
                leader_wire = None
        for i in range(1, size):
            name = f"{leader.metadata.name}-w{i}"
            if self.store.try_get("Pod", name, leader.metadata.namespace):
                continue
            try:
                if leader_wire is not None:
                    self.store.raw_create(
                        "Pod", worker_wire_from_leader(leader_wire, i, size)
                    )
                else:
                    self.store.create(worker_from_leader(leader, i, size))
            except AlreadyExistsError:
                pass

    def _gc_orphan_worker(self, worker: Pod) -> None:
        """Workers (and the gang's headless Service) follow their leader's
        lifecycle — the owner-reference GC contract, done by hand for the
        in-memory store (a real cluster's garbage collector does the same
        from the ownerReferences the expander sets)."""
        for ref in worker.metadata.owner_references:
            if ref.kind == "Pod" and ref.controller:
                if self.store.try_get("Pod", ref.name, worker.metadata.namespace):
                    return
                for kind, name in (
                    ("Pod", worker.metadata.name),
                    ("Service", ref.name),
                ):
                    try:
                        self.store.delete(kind, name, worker.metadata.namespace)
                        log.info(
                            "%s/%s: garbage-collected (leader %s gone)",
                            kind, name, ref.name,
                        )
                    except NotFoundError:
                        pass
                return


def leader_deleted_mapper(store: KubeStore):
    """Watch mapper: a leader's DELETED event enqueues its workers so the
    GC path runs without polling."""
    from nos_tpu.kube.store import DELETED

    def mapper(event) -> List[Request]:
        pod = event.object
        if event.type != DELETED:
            return [Request(name=pod.metadata.name, namespace=pod.metadata.namespace)]
        if pod.metadata.labels.get(MULTIHOST_ROLE_LABEL) != ROLE_LEADER:
            return [Request(name=pod.metadata.name, namespace=pod.metadata.namespace)]
        return [
            Request(name=p.metadata.name, namespace=p.metadata.namespace)
            for p in store.list("Pod", namespace=pod.metadata.namespace)
            if any(
                r.kind == "Pod" and r.name == pod.metadata.name
                for r in p.metadata.owner_references
            )
        ]

    return mapper


# --------------------------------------------------------- shared mutation


def expand_leader_in_place(pod: Pod, accelerator: str, shape: str, n_hosts: int) -> None:
    """The gang-leader rewrite, applied to a Pod object in place: gang
    labels, topology annotation, per-host slice request, distributed-init
    env (rank 0), and the DNS identity that makes the coordinator address
    resolvable. Shared by the controller's store-patch path (in-memory
    suite) and the mutating admission webhook (real clusters, where pod
    labels/requests/env are immutable after admission)."""
    from nos_tpu.parallel.distributed import gang_member_env

    spec = KNOWN_ACCELERATORS[accelerator]
    board_slice = constants.tpu_slice_resource(spec.board_topology)
    gang = pod.metadata.name
    pod.metadata.labels[GANG_NAME_LABEL] = gang
    pod.metadata.labels[GANG_SIZE_LABEL] = str(n_hosts)
    pod.metadata.labels[MULTIHOST_ROLE_LABEL] = ROLE_LEADER
    pod.metadata.annotations[MULTIHOST_TOPOLOGY_ANNOTATION] = shape
    _rewrite_requests(pod, board_slice)
    pod.spec.hostname = pod.metadata.name
    pod.spec.subdomain = gang  # headless Service of the same name
    for container in pod.spec.containers:
        container.env.update(
            gang_member_env(gang, pod.metadata.namespace, 0, n_hosts)
        )


def _rewrite_requests(pod: Pod, board_slice: str) -> None:
    """Replace the oversized plain-chip ask with ONE per-host board slice
    (the leader's share; each worker asks the same). Limits are rewritten
    symmetrically: extended resources require requests == limits on a real
    apiserver."""
    rewritten = False
    for container in pod.spec.containers:
        had_request = container.requests.pop(constants.RESOURCE_TPU, None) is not None
        had_limit = container.limits.pop(constants.RESOURCE_TPU, None) is not None
        if (had_request or had_limit) and not rewritten:
            container.requests[board_slice] = container.requests.get(board_slice, 0) + 1
            container.limits[board_slice] = container.requests[board_slice]
            rewritten = True
    if not rewritten and pod.spec.containers:
        pod.spec.containers[0].requests[board_slice] = 1
        pod.spec.containers[0].limits[board_slice] = 1


def worker_from_leader(leader: Pod, rank: int, size: int) -> Pod:
    """A typed worker pod mirroring the leader (in-memory store path)."""
    from nos_tpu.parallel.distributed import gang_member_env

    name = f"{leader.metadata.name}-w{rank}"
    worker = Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=leader.metadata.namespace,
            labels={
                **{
                    k: v
                    for k, v in leader.metadata.labels.items()
                    if k != MULTIHOST_ROLE_LABEL
                },
                MULTIHOST_ROLE_LABEL: ROLE_WORKER,
            },
            annotations={
                MULTIHOST_TOPOLOGY_ANNOTATION: leader.metadata.annotations.get(
                    MULTIHOST_TOPOLOGY_ANNOTATION, ""
                )
            },
            owner_references=[
                OwnerReference(
                    kind="Pod",
                    name=leader.metadata.name,
                    uid=leader.metadata.uid,
                    controller=True,
                )
            ],
        ),
        spec=copy.deepcopy(leader.spec),
    )
    worker.spec.node_name = ""
    worker.spec.hostname = name
    for container in worker.spec.containers:
        container.env.update(
            gang_member_env(leader.metadata.name, leader.metadata.namespace, rank, size)
        )
    return worker


def worker_wire_from_leader(leader_wire: dict, rank: int, size: int) -> dict:
    """A worker's WIRE pod built from the leader's raw wire object — full
    fidelity for every field the typed projection does not model."""
    import json as _json

    from nos_tpu.parallel.distributed import gang_member_env

    wire = _json.loads(_json.dumps(leader_wire))
    meta = wire.setdefault("metadata", {})
    leader_name = meta.get("name", "")
    namespace = meta.get("namespace", "")
    name = f"{leader_name}-w{rank}"
    labels = dict(meta.get("labels") or {})
    labels[MULTIHOST_ROLE_LABEL] = ROLE_WORKER
    wire["metadata"] = {
        "name": name,
        "namespace": namespace,
        "labels": labels,
        "annotations": {
            MULTIHOST_TOPOLOGY_ANNOTATION: (meta.get("annotations") or {}).get(
                MULTIHOST_TOPOLOGY_ANNOTATION, ""
            )
        },
        "ownerReferences": [
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "name": leader_name,
                "uid": meta.get("uid", ""),
                "controller": True,
            }
        ],
    }
    wire.pop("status", None)
    spec = wire.setdefault("spec", {})
    spec.pop("nodeName", None)
    spec["hostname"] = name
    env_vars = gang_member_env(leader_name, namespace, rank, size)
    for container in spec.get("containers") or []:
        env = [e for e in container.get("env") or [] if e.get("name") not in env_vars]
        env.extend({"name": k, "value": v} for k, v in sorted(env_vars.items()))
        container["env"] = env
    return wire


# ------------------------------------------------------ admission mutation


def admission_mutate_pod(wire_pod: dict, store: KubeStore):
    """JSONPatch ops expanding an oversized pod AT ADMISSION — the only
    point a real cluster allows this rewrite (webhook server route
    ``/mutate-v1-pod``). Returns None (no patch) for pods that need no
    expansion. Ops are computed against the ORIGINAL wire object, so
    unmodeled fields survive untouched."""
    from nos_tpu.kube import serde
    from nos_tpu.kube.apistore import _overlay_containers

    pod = serde.pod_from_wire(wire_pod)
    if pod.metadata.labels.get(MULTIHOST_ROLE_LABEL):
        return None  # already expanded (or one of our own workers)
    if pod.spec.node_name:
        return None
    expander = MultihostExpander(store)
    accelerator = expander._cluster_accelerator()
    if accelerator is None:
        return None
    chips = expander._oversized_chips(pod, accelerator)
    if chips <= 0:
        return None
    profile = multihost_profile_for_chips(chips, accelerator)
    if profile is None:
        return None
    shape, n_hosts = profile
    expand_leader_in_place(pod, accelerator, shape, n_hosts)
    projected = serde.pod_to_wire(pod)
    ops = []
    for key in ("labels", "annotations"):
        merged = {
            **((wire_pod.get("metadata") or {}).get(key) or {}),
            **(projected["metadata"].get(key) or {}),
        }
        ops.append({"op": "add", "path": f"/metadata/{key}", "value": merged})
    ops.append(
        {
            "op": "replace",
            "path": "/spec/containers",
            "value": _overlay_containers(
                (wire_pod.get("spec") or {}).get("containers"),
                projected["spec"].get("containers"),
            ),
        }
    )
    ops.append({"op": "add", "path": "/spec/hostname", "value": pod.spec.hostname})
    ops.append({"op": "add", "path": "/spec/subdomain", "value": pod.spec.subdomain})
    return ops
