"""Persistent-snapshot maintenance for incremental replanning.

The partitioner used to rebuild its ClusterSnapshot from the whole store
on every cycle and the planner re-walked the world from scratch — O(cluster)
per replan even when nothing changed. This module keeps ONE base snapshot
alive across cycles per partitioning mode and turns the store deltas the
cycle boundary drains into a **dirty set** of node names:

- a Node event dirties that node;
- a Pod event dirties the node the pod is (or was) bound to — unbound
  pending pods don't touch any node's observed state;
- an ElasticQuota SPEC change (min/max bounds, create, delete) forces a
  full rebuild (quota bounds are cluster-wide planner inputs with no
  per-node locality). Status-only quota updates — the usage bumps the
  quota controller writes after every bind — are ignored: the snapshot
  carries no quota state and every quota-reading plugin
  (CapacityScheduling) is verdict-uncacheable, re-reading the live
  store on each trial, so no retained structure can go stale. Without
  this distinction steady state never exists: each plan's own binds
  trigger a usage write that would force a rebuild next cycle;
- a Node entering or leaving this mode's scope (delete, label flip,
  becoming/ceasing to be a TPU or sharing node) changes the snapshot's
  SHAPE and forces a full rebuild — the snapshot has no add/remove API by
  design, so shape changes can never half-apply;
- an accelerator-generation change on a node forces a full rebuild
  (request normalization and the accelerator list are cross-node inputs);
- a drain overflow (event storm) forces a full rebuild — classifying the
  storm would cost more than replanning.

Dirty nodes are re-snapshotted from the live store through the taker's
``take_snapshot_node`` — the exact constructor the full take uses — and
swapped into the base via ``ClusterSnapshot.refresh_node``, which keeps
the free pool and anti-affinity aggregates exact and stamps a fresh
mutation tick so the planner's version-keyed memos for the old state
become unreachable. Re-refreshing a node whose change was already visible
to the previous rebuild is therefore harmless (idempotent), which is what
makes the watch-attach / first-build race benign.

The maintainer returns ``(snapshot, dirty)`` where a full rebuild reports
every node as dirty; the planner maps a fresh snapshot object or an
oversized dirty set to its from-scratch fallback on its own, so this
module never needs to agree with the planner's threshold.
"""
from __future__ import annotations

import logging
import queue
import time
from typing import Optional, Set, Tuple

from nos_tpu.partitioning.core.snapshot import ClusterSnapshot
from nos_tpu.util import metrics

log = logging.getLogger("nos_tpu.partitioner")

# Store kinds whose deltas the dirty-set derivation understands; anything
# else never reaches the planner's inputs.
WATCH_KINDS = ("ElasticQuota", "Node", "Pod")

# Above this many drained events per cycle the per-event classification
# costs more than a rebuild.
MAX_EVENTS_PER_DRAIN = 10_000


class IncrementalSnapshotMaintainer:
    """Owns the persistent base ClusterSnapshot for one partitioner mode
    (tpu or sharing) and derives the per-cycle dirty set from store
    deltas. Single-threaded by contract: only the partitioner's batch
    loop calls :meth:`snapshot` (the store's watch queue is the only
    cross-thread hand-off, and it is a thread-safe queue)."""

    def __init__(self, store, snapshot_taker, kind: str = "tpu") -> None:
        self.store = store
        self.taker = snapshot_taker
        self.kind = kind
        self._queue = None
        self._base: Optional[ClusterSnapshot] = None
        # Names currently in the base — the shape the snapshot was built
        # with. Kept here so scope checks never walk the snapshot.
        self._names: Set[str] = set()
        # Quota key -> spec signature as of the last rebuild, so status-
        # only quota updates can be told apart from bound changes.
        self._quota_specs: dict = {}
        # Test/observability taps.
        self.full_rebuilds = 0
        self.nodes_refreshed = 0
        # Phase histogram children, cached (labels() locks the registry).
        self._phase_drain = metrics.PARTITIONER_PHASE.labels(kind=kind, phase="drain")
        self._phase_refresh_h = metrics.PARTITIONER_PHASE.labels(kind=kind, phase="refresh")

    # ------------------------------------------------------------- entry

    def snapshot(self, cluster_state) -> Tuple[ClusterSnapshot, Set[str]]:
        """The base snapshot plus the names of nodes refreshed since the
        previous call (a full rebuild reports every node dirty). Must be
        called once per plan cycle, AFTER the caller read its revision
        watermark — the maintainer reads the live store, so draining first
        would widen the recorded race window replay has to reproduce."""
        if self._queue is None:
            self._queue = self.store.watch(
                set(WATCH_KINDS), name=f"partitioner-maintainer-{self.kind}"
            )
            # Discard the list+watch ADDED replay of existing objects —
            # the first build below reads the live store directly.
            self._timed_drain()
            return self._timed_rebuild(cluster_state)
        events = self._timed_drain()
        if events is None:
            log.info(
                "partitioner[%s]: delta drain overflow; rebuilding snapshot",
                self.kind,
            )
            return self._timed_rebuild(cluster_state)
        dirty, rebuild = self._classify(events)
        if not rebuild:
            refreshed = self._timed_refresh(dirty)
            if refreshed is not None:
                return self._base, refreshed
        return self._timed_rebuild(cluster_state)

    # ------------------------------------------------------ phase timing
    # Thin wrappers so every cycle's drain/refresh(+rebuild) lands in the
    # nos_tpu_partitioner_phase_seconds histogram (a rebuild is the
    # refresh phase taken the expensive way, so it shares that label).

    def _timed_drain(self) -> "Optional[list]":
        t0 = time.monotonic()
        try:
            return self._drain()
        finally:
            self._phase_drain.observe(time.monotonic() - t0)

    def _timed_refresh(self, dirty: Set[str]) -> Optional[Set[str]]:
        t0 = time.monotonic()
        try:
            return self._refresh(dirty)
        finally:
            self._phase_refresh_h.observe(time.monotonic() - t0)

    def _timed_rebuild(self, cluster_state) -> Tuple[ClusterSnapshot, Set[str]]:
        t0 = time.monotonic()
        try:
            return self._rebuild(cluster_state)
        finally:
            self._phase_refresh_h.observe(time.monotonic() - t0)

    # ----------------------------------------------------------- internals

    def _drain(self) -> "Optional[list]":
        """Every queued event, or None on overflow (queue left empty)."""
        events: list = []
        q = self._queue
        overflow = False
        while True:
            try:
                event = q.get_nowait()
            except queue.Empty:
                return None if overflow else events
            if not overflow:
                events.append(event)
                overflow = len(events) > MAX_EVENTS_PER_DRAIN

    def _classify(self, events) -> Tuple[Set[str], bool]:
        """(dirty node names, full-rebuild?). Conservative by design: any
        delta whose node-local footprint is unclear escalates to a
        rebuild rather than guessing."""
        dirty: Set[str] = set()
        for event in events:
            kind = event.kind
            if kind == "ElasticQuota":
                meta = event.object.metadata
                key = f"{meta.namespace}/{meta.name}"
                if event.type == "DELETED":
                    if key in self._quota_specs:
                        return dirty, True
                    continue
                sig = _quota_spec_signature(event.object)
                if self._quota_specs.get(key) == sig:
                    continue  # status-only update: planner-neutral
                return dirty, True
            obj = event.object
            if kind == "Pod":
                node_name = obj.spec.node_name
                if node_name and node_name in self._names:
                    dirty.add(node_name)
                continue
            # Node event. Deleting a node we snapshot is a shape change;
            # deletes of out-of-scope nodes never mattered.
            name = obj.metadata.name
            if event.type == "DELETED":
                if name in self._names:
                    return dirty, True
                continue
            # ADDED/MODIFIED: scope membership is resolved against the
            # live store in _refresh (events can be stale).
            dirty.add(name)
        return dirty, False

    def _refresh(self, dirty: Set[str]) -> Optional[Set[str]]:
        """Re-snapshot each dirty node from the live store into the base.
        Returns the refreshed names, or None when a scope transition was
        discovered (caller rebuilds). Two copy-free store passes fetch
        the dirty nodes and their bound pods — no per-node index scans,
        no walk of the untouched part of the base."""
        if not dirty:
            return set()
        nodes_by_name = {}
        for node in self.store.list("Node", copy=False):
            if node.metadata.name in dirty:
                nodes_by_name[node.metadata.name] = node
        pods_by_node: dict = {name: [] for name in dirty}
        for pod in self.store.list("Pod", copy=False):
            bucket = pods_by_node.get(pod.spec.node_name)
            if bucket is not None and pod.status.phase in ("Pending", "Running"):
                bucket.append(pod)
        refreshed: Set[str] = set()
        for name in sorted(dirty):
            node = nodes_by_name.get(name)
            in_base = name in self._names
            snap_node = (
                self.taker.take_snapshot_node(node, pods_by_node[name])
                if node is not None
                else None
            )
            if snap_node is None:
                if in_base:
                    # Left our scope (deleted between drain and list, or
                    # label/eligibility flip): shape change.
                    return None
                continue  # never ours — another mode's node, ignore
            if not in_base:
                return None  # entered our scope: shape change
            old = self._base.get_node(name)
            if getattr(snap_node.partitionable, "accelerator", None) != getattr(
                old.partitionable, "accelerator", None
            ):
                # Generation swap changes request normalization for every
                # pod signature — cheaper to re-key the world than reason
                # about which memos survive.
                return None
            self._base.refresh_node(name, snap_node)
            refreshed.add(name)
        self.nodes_refreshed += len(refreshed)
        return refreshed

    def _rebuild(self, cluster_state) -> Tuple[ClusterSnapshot, Set[str]]:
        self._base = self.taker.take_snapshot(cluster_state, store=self.store)
        self._names = set(self._base.get_nodes())
        self._quota_specs = {
            f"{q.metadata.namespace}/{q.metadata.name}": _quota_spec_signature(q)
            for q in self.store.list("ElasticQuota", copy=False)
        }
        self.full_rebuilds += 1
        return self._base, set(self._names)


def _quota_spec_signature(quota) -> tuple:
    """Canonical hash input for the planner-relevant part of a quota: its
    bounds. Status (usage) is derived state the planner re-reads live."""
    spec = quota.spec
    return (
        tuple(sorted(spec.min.items())),
        tuple(sorted(spec.max.items())),
    )


class PoolShardedMaintainer:
    """Layered over :class:`IncrementalSnapshotMaintainer`: keeps the
    global base (and its drain/classify machinery) AND one persistent
    per-pool ClusterSnapshot per planning pool, so each pool's planner
    gets its own incremental base with its own mutation clock, dirty set
    and memos.

    Per cycle: the inner maintainer refreshes the global base and yields
    the dirty set; the pool partition is recomputed as a pure function of
    (snapshot, pending, quota bounds) through an incrementally maintained
    selector index; then either

    - the node->pool mapping is UNCHANGED: each dirty node's fresh state
      is cloned from the global base into its pool snapshot via
      ``refresh_node`` (pool memos for untouched nodes survive), or
    - the mapping CHANGED (a gang now spans two pools, a label moved a
      node, the graph connected into the mega-pool): every pool snapshot
      is rebuilt from the global base and every pool reports fully dirty
      — the memo flush the partition-stability test pins as happening
      ONLY on real partition changes, never on no-op cycles.

    Single-threaded by contract, like the inner maintainer; the per-pool
    snapshots it returns may then be planned concurrently because they
    share no mutable state (every SnapshotNode is an exclusive clone)."""

    def __init__(self, store, snapshot_taker, kind: str = "tpu") -> None:
        from nos_tpu.partitioning.core.pools import SelectorPoolIndex

        self.inner = IncrementalSnapshotMaintainer(store, snapshot_taker, kind)
        self.kind = kind
        self.store = store
        self._index = SelectorPoolIndex()
        self._base: Optional[ClusterSnapshot] = None
        self._partition = None  # the previous cycle's PoolPartition
        self._pool_bases: dict = {}
        # Set by shard(): whether this cycle rebuilt the pool snapshots
        # (cold start, global rebuild, partition change, forced); the
        # controller re-creates per-pool planners exactly then.
        self.last_rebuilt = False
        self._force_rebuild = False
        # Test/observability taps.
        self.pool_rebuilds = 0

    def force_rebuild(self) -> None:
        """Next shard() rebuilds pool snapshots regardless of partition
        stability — the merge-conflict escape hatch."""
        self._force_rebuild = True

    def shard(self, cluster_state, pending_pods):
        """(global snapshot, global dirty, partition, pool snapshots,
        pool dirty sets) for one plan cycle."""
        from nos_tpu.partitioning.core.pools import (
            partition_pools,
            split_snapshot,
        )

        snapshot, dirty = self.inner.snapshot(cluster_state)
        nodes = snapshot.get_nodes()
        if snapshot is not self._base:
            # Inner rebuild produced a fresh base object: every incremental
            # structure derived from the old one is meaningless.
            self._base = snapshot
            self._index.rebuild(snapshot)
        else:
            for name in dirty:
                snap_node = nodes.get(name)
                if snap_node is not None:
                    self._index.note(name, snap_node)
        quotas = list(self.store.list("ElasticQuota", copy=False))
        partition = partition_pools(
            snapshot, pending_pods, quotas=quotas, selector_index=self._index
        )
        rebuild = (
            self._force_rebuild
            or self._partition is None
            or partition.node_pool != self._partition.node_pool
        )
        self._force_rebuild = False
        if rebuild:
            self._pool_bases = split_snapshot(snapshot, partition)
            pool_dirty = {
                pool: set(base.get_nodes())
                for pool, base in self._pool_bases.items()
            }
            self.pool_rebuilds += 1
        else:
            pool_dirty = {pool: set() for pool in partition.pools}
            for name in dirty:
                pool = partition.node_pool.get(name)
                if pool is None:
                    continue
                clone = nodes[name].plan_clone()
                self._pool_bases[pool].refresh_node(name, clone)
                pool_dirty[pool].add(name)
        self._partition = partition
        self.last_rebuilt = rebuild
        return snapshot, dirty, partition, self._pool_bases, pool_dirty
