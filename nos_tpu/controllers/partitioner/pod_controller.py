"""StatePodController: keeps ClusterState pod usage fresh on pod events
(reference internal/controllers/gpupartitioner/pod_controller.go:47-112),
lazily adding unknown nodes.
"""
from __future__ import annotations

import logging
from typing import Optional

from nos_tpu.api.v1alpha1 import constants
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import ClusterState

log = logging.getLogger("nos_tpu.partitioner")


class StatePodController:
    def __init__(self, store: KubeStore, cluster_state: ClusterState) -> None:
        self.store = store
        self.cluster_state = cluster_state

    def reconcile(self, req: Request) -> Optional[Result]:
        pod = self.store.try_get("Pod", req.name, req.namespace)
        if pod is None:
            # Object gone: purge any stale binding we may hold.
            from nos_tpu.kube.objects import ObjectMeta, Pod as PodObj

            ghost = PodObj(metadata=ObjectMeta(name=req.name, namespace=req.namespace))
            self.cluster_state.delete_pod(ghost)
            return None
        node_name = pod.spec.node_name
        if node_name and self.cluster_state.get_node(node_name) is None:
            node = self.store.try_get("Node", node_name)
            if node is not None:
                pods = [
                    p
                    for p in self.store.list_by_index(
                        "Pod", constants.INDEX_POD_NODE, node_name
                    )
                    if p.status.phase in ("Pending", "Running")
                ]
                self.cluster_state.update_node(node, pods)
                return None
        self.cluster_state.update_pod_usage(pod)
        return None
