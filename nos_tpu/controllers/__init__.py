"""Controllers: the suite's reconcilers (reference internal/controllers/)."""
