"""Create-time validation (reference
pkg/api/nos.nebuly.com/v1alpha1/elasticquota_webhook.go:31-97 and
compositeelasticquota_webhook.go): at most one ElasticQuota per namespace;
an EQ's namespace must not be covered by any CompositeElasticQuota, and
symmetrically a CEQ cannot cover a namespace that already has an EQ covered
by another CEQ. Additionally min ≤ max where both are set.
"""
from __future__ import annotations

from nos_tpu.kube.store import AdmissionError, KubeStore


def _validate_min_max(spec) -> None:
    for name, min_qty in spec.min.items():
        if name in spec.max and spec.max[name] < min_qty:
            raise AdmissionError(
                f"spec.max[{name}]={spec.max[name]} is below spec.min={min_qty}"
            )


def validate_elastic_quota(quota, store: KubeStore) -> None:
    _validate_min_max(quota.spec)
    ns = quota.metadata.namespace
    for existing in store.list("ElasticQuota", namespace=ns):
        if existing.metadata.name != quota.metadata.name:
            raise AdmissionError(
                f"namespace {ns} already has ElasticQuota {existing.metadata.name}"
            )
    for ceq in store.list("CompositeElasticQuota"):
        if ns in ceq.spec.namespaces:
            raise AdmissionError(
                f"namespace {ns} is covered by CompositeElasticQuota "
                f"{ceq.metadata.name}"
            )


def validate_composite_elastic_quota(quota, store: KubeStore) -> None:
    _validate_min_max(quota.spec)
    for other in store.list("CompositeElasticQuota"):
        if other.metadata.name == quota.metadata.name and (
            other.metadata.namespace == quota.metadata.namespace
        ):
            continue
        overlap = set(other.spec.namespaces) & set(quota.spec.namespaces)
        if overlap:
            raise AdmissionError(
                f"namespaces {sorted(overlap)} already covered by "
                f"CompositeElasticQuota {other.metadata.name}"
            )


def register_elasticquota_webhooks(store: KubeStore) -> None:
    store.register_admission("ElasticQuota", validate_elastic_quota)
    store.register_admission("CompositeElasticQuota", validate_composite_elastic_quota)
