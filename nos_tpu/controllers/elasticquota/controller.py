"""Quota accounting & over-quota labeling.

Reference internal/controllers/elasticquota/elasticquota_controller.go:66-189
+ elasticquota.go:38-149: on quota change or pod phase transition, list the
namespace's running pods, walk them in deterministic order accumulating
used quota, label each pod in-quota/over-quota (the scheduler's preemption
victims are picked by this label), and publish status.used.

CompositeElasticQuota does the same over a namespace *list* and deletes
overlapping per-namespace quotas (compositeelasticquota_controller.go:110-137).
"""
from __future__ import annotations

import logging
from typing import List, Optional

from nos_tpu.api.v1alpha1 import labels as labels_api
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.objects import Pod, PodPhase, ResourceList
from nos_tpu.kube.store import KubeStore
from nos_tpu.util import resources as res

log = logging.getLogger("nos_tpu.elasticquota")


def sort_pods_for_quota(pods: List[Pod]) -> List[Pod]:
    """Deterministic accounting order (reference elasticquota.go:77-104):
    older pods first (they claimed quota first), then higher priority, then
    smaller aggregate request, then name."""
    return sorted(
        pods,
        key=lambda p: (
            p.metadata.creation_timestamp,
            -p.spec.priority,
            sum(res.with_aggregate_tpu_chips(res.compute_pod_request(p)).values()),
            p.metadata.namespace,
            p.metadata.name,
        ),
    )


def _filter_to_min(request: ResourceList, min_resources: ResourceList) -> ResourceList:
    """Quota only tracks resources named in spec.min (elasticquota.go:64-69)."""
    return {k: v for k, v in request.items() if k in min_resources}


class _QuotaReconcilerBase:
    def __init__(
        self,
        store: KubeStore,
        chip_memory_gb: int | None = None,
        recorder=None,
        flight_recorder=None,
    ) -> None:
        from nos_tpu.api.v1alpha1 import constants

        self.store = store
        self.chip_memory_gb = chip_memory_gb or constants.DEFAULT_TPU_CHIP_MEMORY_GB
        # Optional kube/events.py EventRecorder: QuotaBorrowed/QuotaReclaimed
        # on every capacity-label flip, so "why is my pod a preemption
        # victim" is answerable from kubectl-style events.
        self.recorder = recorder
        # Optional record/recorder.py FlightRecorder: quota reconciles are
        # logged as decision records (informational on replay — the label
        # flips themselves arrive via the recorded pod deltas).
        self.flight_recorder = flight_recorder

    def _running_pods(self, namespaces: List[str]) -> List[Pod]:
        pods: List[Pod] = []
        for ns in namespaces:
            pods.extend(
                p
                for p in self.store.list("Pod", namespace=ns)
                if p.status.phase == PodPhase.RUNNING
            )
        return pods

    def _reconcile_quota(self, quota, namespaces: List[str]) -> None:
        from nos_tpu.util.tracing import TRACER

        with TRACER.span(
            "elasticquota.reconcile",
            quota=f"{quota.metadata.namespace}/{quota.metadata.name}",
        ):
            self._reconcile_quota_traced(quota, namespaces)

    def _reconcile_quota_traced(self, quota, namespaces: List[str]) -> None:
        # Watermark BEFORE this reconcile's own writes: the flips below are
        # consequences of the state at this revision, not inputs to it.
        revision = self.store.revision
        flips: List[List[str]] = []
        pods = sort_pods_for_quota(self._running_pods(namespaces))
        min_resources = quota.spec.min
        used: ResourceList = {}
        for pod in pods:
            request = _filter_to_min(
                res.with_aggregate_tpu_chips(
                    res.compute_pod_request(pod), self.chip_memory_gb
                ),
                min_resources,
            )
            candidate = res.sum_resources(used, request)
            in_quota = res.fits(min_resources, candidate)
            desired_label = (
                labels_api.CAPACITY_IN_QUOTA if in_quota else labels_api.CAPACITY_OVER_QUOTA
            )
            previous_label = pod.metadata.labels.get(labels_api.CAPACITY_LABEL)
            if previous_label != desired_label:
                self.store.patch_labels(
                    "Pod",
                    pod.metadata.name,
                    pod.metadata.namespace,
                    {labels_api.CAPACITY_LABEL: desired_label},
                )
                self._record_capacity_flip(quota, pod, in_quota, previous_label)
                flips.append([pod.namespaced_name, desired_label])
            used = candidate

        if quota.status.used != used:
            def mutate(q):
                q.status.used = used

            self.store.patch_merge(
                quota.kind, quota.metadata.name, quota.metadata.namespace, mutate
            )

        if self.flight_recorder is not None:
            self.flight_recorder.record_quota_reconcile(
                quota=f"{quota.metadata.namespace}/{quota.metadata.name}".lstrip("/"),
                revision=revision,
                used=dict(used),
                flips=flips,
            )

    def _record_capacity_flip(
        self, quota, pod: Pod, in_quota: bool, previous_label
    ) -> None:
        if self.recorder is None:
            return
        from nos_tpu.api.v1alpha1 import constants

        quota_name = f"{quota.metadata.namespace}/{quota.metadata.name}".lstrip("/")
        if in_quota:
            # A pod's FIRST labeling as in-quota is the steady state, not a
            # reclaim — only an over-quota -> in-quota flip is news.
            if previous_label != labels_api.CAPACITY_OVER_QUOTA:
                return
            self.recorder.record(
                pod,
                constants.EVENT_REASON_QUOTA_RECLAIMED,
                f"{pod.namespaced_name} back within {quota.kind} "
                f"{quota_name} guaranteed quota",
            )
        else:
            self.recorder.record(
                pod,
                constants.EVENT_REASON_QUOTA_BORROWED,
                f"{pod.namespaced_name} running on capacity borrowed over "
                f"{quota.kind} {quota_name} min (preemptible)",
                type="Warning",
            )


class ElasticQuotaReconciler(_QuotaReconcilerBase):
    def reconcile(self, req: Request) -> Optional[Result]:
        quota = self.store.try_get("ElasticQuota", req.name, req.namespace)
        if quota is None:
            return None
        self._reconcile_quota(quota, [quota.metadata.namespace])
        return None


class CompositeElasticQuotaReconciler(_QuotaReconcilerBase):
    def reconcile(self, req: Request) -> Optional[Result]:
        quota = self.store.try_get("CompositeElasticQuota", req.name, req.namespace)
        if quota is None:
            return None
        # A CEQ shadows per-namespace EQs for its namespaces: delete overlaps
        # (compositeelasticquota_controller.go:110-137).
        for eq in self.store.list("ElasticQuota"):
            if eq.metadata.namespace in quota.spec.namespaces:
                log.info(
                    "deleting ElasticQuota %s overlapped by CompositeElasticQuota %s",
                    eq.metadata.namespace + "/" + eq.metadata.name,
                    quota.metadata.name,
                )
                self.store.delete("ElasticQuota", eq.metadata.name, eq.metadata.namespace)
        self._reconcile_quota(quota, list(quota.spec.namespaces))
        return None


def pod_to_quota_requests(store: KubeStore, event) -> List[Request]:
    """Watch mapper: a pod event maps to the quota(s) covering its namespace
    (reference Watches mapping elasticquota_controller.go:140-164)."""
    ns = event.object.metadata.namespace
    out: List[Request] = []
    for eq in store.list("ElasticQuota", namespace=ns):
        out.append(Request(name=eq.metadata.name, namespace=ns))
    for ceq in store.list("CompositeElasticQuota"):
        if ns in ceq.spec.namespaces:
            out.append(
                Request(name=ceq.metadata.name, namespace=ceq.metadata.namespace)
            )
    return out
