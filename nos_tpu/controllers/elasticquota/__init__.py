"""ElasticQuota / CompositeElasticQuota reconcilers + webhooks
(reference internal/controllers/elasticquota/)."""

from nos_tpu.controllers.elasticquota.controller import (
    CompositeElasticQuotaReconciler,
    ElasticQuotaReconciler,
)
from nos_tpu.controllers.elasticquota.webhooks import (
    register_elasticquota_webhooks,
    validate_composite_elastic_quota,
    validate_elastic_quota,
)

__all__ = [
    "CompositeElasticQuotaReconciler",
    "ElasticQuotaReconciler",
    "register_elasticquota_webhooks",
    "validate_composite_elastic_quota",
    "validate_elastic_quota",
]
