"""SharingReporter: shared-slice state → status annotations (reporter only).

The gpuagent analogue (reference internal/controllers/gpuagent/
reporter.go:50-110): sharing nodes have no local actuator — the device
plugin actuates via its ConfigMap — so the node agent only mirrors actual
device state into ``status-tpu-<chip>-<profile>-<free|used>`` annotations
for the planner's SharingNode model. Like the reference agent refusing to
run on MIG nodes (cmd/gpuagent/gpuagent.go:106-114), it skips nodes
labeled for the tpu (agent-actuated) mode.
"""
from __future__ import annotations

import logging
from typing import Optional

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1.labels import PARTITIONING_LABEL, PartitioningKind
from nos_tpu.device.sharing import SharedSliceClient
from nos_tpu.device.types import group_geometries
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.store import KubeStore, NotFoundError

log = logging.getLogger("nos_tpu.sharingagent")


class SharingReporter:
    def __init__(
        self,
        store: KubeStore,
        client: SharedSliceClient,
        node_name: str,
        report_interval_seconds: float = 10.0,
    ) -> None:
        self.store = store
        self.client = client
        self.node_name = node_name
        self.interval = report_interval_seconds

    def reconcile(self, req: Request) -> Optional[Result]:
        if req.name != self.node_name:
            return None
        try:
            node = self.store.get("Node", self.node_name)
        except NotFoundError:
            return None
        if (
            node.metadata.labels.get(PARTITIONING_LABEL, "")
            == PartitioningKind.TPU
        ):
            log.warning(
                "sharingagent on %s: node is labeled for agent-actuated "
                "partitioning, refusing to report",
                self.node_name,
            )
            return Result(requeue_after=self.interval)

        grouped = group_geometries(self.client.get_devices(self.node_name))
        desired_status = annot.status_from_devices(
            free=grouped["free"], used=grouped["used"]
        )
        current_status = {
            k: v
            for k, v in node.metadata.annotations.items()
            # Own only sharing-profile entries: on hybrid nodes the
            # topology entries (and the plan id) belong to the tpuagent.
            if annot.is_sharing_status_key(k)
        }
        if current_status != desired_status:
            patch = {k: None for k in current_status}
            patch.update(desired_status)
            self.store.patch_annotations("Node", self.node_name, "", patch)
            log.info("sharingagent: %s status updated", self.node_name)
        return Result(requeue_after=self.interval)
