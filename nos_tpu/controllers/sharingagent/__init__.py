from nos_tpu.controllers.sharingagent.reporter import SharingReporter

__all__ = ["SharingReporter"]
