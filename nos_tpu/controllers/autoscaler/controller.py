"""The ModelServing reconciler: burn-rate verdicts to replica Pods.

Pure API-server contract (the architecture's one rule): this controller
only reads signals and writes Pods + ModelServing status + node
annotations. It never talks to the scheduler or partitioner — replica
pods request `google.com/tpu` chips and the rest of the suite places and
carves for them exactly as it does for hand-written workloads.

Replica pods are named ``<ms>-replica-<i>`` with dense indices: scale-up
creates the lowest missing indices, scale-down deletes from the top, so
any (current, desired) pair maps to exactly one set of writes and the
reconciler is idempotent under watch replays.

Scale-to-zero stamps a cold-start grace reservation (annotations) on the
nodes the replicas vacated: the capacity ledger books that idle window to
`autoscaler-grace` instead of `no-demand`, and the reservation expires on
its own clock so held boards cannot leak.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from nos_tpu.api.config import AutoscalerConfig
from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.api.v1alpha1.modelserving import ModelServing
from nos_tpu.controllers.autoscaler import policy
from nos_tpu.controllers.autoscaler.signals import SignalRegistry
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.objects import Container, ObjectMeta, Pod, PodSpec
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL
from nos_tpu.util import metrics
from nos_tpu.util.tracing import TRACER

log = logging.getLogger("nos_tpu.autoscaler")


def serving_key(ms: ModelServing) -> str:
    """Label value tying replica pods to their ModelServing (label values
    cannot contain '/', so the namespaced name is dot-joined)."""
    return f"{ms.metadata.namespace}.{ms.metadata.name}"


def replica_name(ms_name: str, index: int) -> str:
    return f"{ms_name}-replica-{index}"


class ModelServingReconciler:
    def __init__(
        self,
        store: KubeStore,
        config: Optional[AutoscalerConfig] = None,
        signals: Optional[SignalRegistry] = None,
        recorder=None,
    ) -> None:
        self.store = store
        self.config = config or AutoscalerConfig()
        self.signals = signals or SignalRegistry()
        self.recorder = recorder
        # serving key -> model label last exported on AUTOSCALER_REPLICAS,
        # so _collect_orphans can reset the series after the ModelServing
        # object (and its spec.model) is gone.
        self._exported_models: Dict[str, str] = {}

    # ------------------------------------------------------------ helpers

    def replica_pods(self, ms: ModelServing) -> List[Pod]:
        key = serving_key(ms)
        pods = [
            p
            for p in self.store.list("Pod", namespace=ms.metadata.namespace)
            if p.metadata.labels.get(labels.MODEL_SERVING_LABEL) == key
        ]
        return sorted(pods, key=lambda p: p.metadata.name)

    def _build_replica(self, ms: ModelServing, index: int) -> Pod:
        name = replica_name(ms.metadata.name, index)
        chips = ms.spec.chips_per_replica
        requests = {constants.RESOURCE_TPU: chips}
        return Pod(
            metadata=ObjectMeta(
                name=name,
                namespace=ms.metadata.namespace,
                labels={
                    labels.MODEL_SERVING_LABEL: serving_key(ms),
                    # Each replica is its own gang of one: replicas must
                    # place independently (losing one cannot wedge the
                    # rest), but still go through the gang plugin's
                    # all-or-nothing carve handshake.
                    GANG_NAME_LABEL: name,
                    GANG_SIZE_LABEL: "1",
                },
            ),
            spec=PodSpec(
                containers=[
                    Container(requests=dict(requests), limits=dict(requests))
                ],
                scheduler_name=ms.spec.scheduler_name,
            ),
        )

    def _record(self, ms: ModelServing, reason_attr: str, message: str) -> None:
        if self.recorder is None:
            return
        if reason_attr == "ScaledUp":
            self.recorder.record(ms, constants.EVENT_REASON_SCALED_UP, message)
        elif reason_attr == "ScaledDown":
            self.recorder.record(ms, constants.EVENT_REASON_SCALED_DOWN, message)
        elif reason_attr == "ScaledToZero":
            self.recorder.record(
                ms, constants.EVENT_REASON_SCALED_TO_ZERO, message
            )
        elif reason_attr == "ColdStart":
            self.recorder.record(ms, constants.EVENT_REASON_COLD_START, message)

    # ------------------------------------------------- grace reservations

    def _reserve_nodes(self, ms: ModelServing, node_names: List[str], now: float) -> None:
        if ms.spec.cold_start_grace_seconds <= 0:
            return
        until = now + ms.spec.cold_start_grace_seconds
        for node in sorted(set(n for n in node_names if n)):
            try:
                self.store.patch_annotations(
                    "Node",
                    node,
                    "",
                    {
                        annot.AUTOSCALER_RESERVED: serving_key(ms),
                        annot.AUTOSCALER_RESERVED_UNTIL: f"{until:.6f}",
                    },
                )
            except NotFoundError:
                continue

    def _sweep_reservations(self, ms: ModelServing, now: float, release_all: bool) -> float:
        """Clear this model's expired grace reservations; return the next
        expiry (+inf when none held) so reconcile can requeue for it."""
        key = serving_key(ms)
        next_expiry = float("inf")
        for node in self.store.list("Node"):
            ann = node.metadata.annotations
            if ann.get(annot.AUTOSCALER_RESERVED) != key:
                continue
            try:
                until = float(ann.get(annot.AUTOSCALER_RESERVED_UNTIL, "0"))
            except ValueError:
                until = 0.0
            if release_all or now >= until:
                try:
                    self.store.patch_annotations(
                        "Node",
                        node.metadata.name,
                        "",
                        {
                            annot.AUTOSCALER_RESERVED: None,
                            annot.AUTOSCALER_RESERVED_UNTIL: None,
                        },
                    )
                except NotFoundError:
                    continue
            else:
                next_expiry = min(next_expiry, until)
        return next_expiry

    # ----------------------------------------------------------- reconcile

    def reconcile(self, req: Request) -> Optional[Result]:
        ms = self.store.try_get("ModelServing", req.name, req.namespace)
        if ms is None:
            self._collect_orphans(req)
            return None
        with TRACER.span(
            "autoscaler.reconcile", model_serving=f"{req.namespace}/{req.name}"
        ):
            return self._reconcile(ms)

    def _collect_orphans(self, req: Request) -> None:
        """A deleted ModelServing's replicas don't outlive it (the real
        CRD would use ownerReferences + GC)."""
        key = f"{req.namespace}.{req.name}"
        for p in self.store.list("Pod", namespace=req.namespace):
            if p.metadata.labels.get(labels.MODEL_SERVING_LABEL) == key:
                try:
                    self.store.delete("Pod", p.metadata.name, p.metadata.namespace)
                except NotFoundError:
                    pass
        # Label reset: the replica gauge series die with the object. If
        # another live ModelServing shares the model label its next
        # reconcile re-creates the series at the true value.
        model = self._exported_models.pop(key, None)
        if model is not None:
            for state in ("desired", "ready"):
                metrics.AUTOSCALER_REPLICAS.remove(model=model, state=state)

    def _reconcile(self, ms: ModelServing) -> Optional[Result]:
        now = self.signals.now()
        sig = self.signals.get(ms.spec.model)
        pods = self.replica_pods(ms)
        live = [p for p in pods if p.metadata.deletion_timestamp is None]
        current = len(live)
        ready = sum(1 for p in live if p.spec.node_name)

        decision = policy.decide(
            ms.spec,
            current,
            sig,
            self.config,
            now,
            last_transition_t=ms.status.last_transition_t,
        )
        metrics.AUTOSCALER_DECISIONS.labels(verdict=decision.verdict).inc()
        metrics.AUTOSCALER_REPLICAS.labels(
            model=ms.spec.model, state="desired"
        ).set(decision.desired)
        metrics.AUTOSCALER_REPLICAS.labels(model=ms.spec.model, state="ready").set(
            ready
        )
        self._exported_models[serving_key(ms)] = ms.spec.model

        cold_starting = decision.verdict == policy.VERDICT_COLD_START
        if decision.desired > current:
            self._scale_up(ms, live, decision, cold_starting)
        elif decision.desired < current:
            self._scale_down(ms, live, decision, now)

        # Grace reservations: release on demand's return (the cold start
        # lands on the still-carved boards), expire on their own clock.
        next_expiry = self._sweep_reservations(
            ms, now, release_all=cold_starting or decision.desired > 0
        )

        self._update_status(ms, decision, current, ready, now)

        requeue_after = self.config.resync_seconds
        if next_expiry != float("inf"):
            requeue_after = min(requeue_after, max(0.05, next_expiry - now))
        return Result(requeue_after=requeue_after)

    def _scale_up(
        self,
        ms: ModelServing,
        live: List[Pod],
        decision: policy.Decision,
        cold_starting: bool,
    ) -> None:
        have = {p.metadata.name for p in live}
        created = []
        for i in range(decision.desired):
            name = replica_name(ms.metadata.name, i)
            if name in have:
                continue
            if len(have) + len(created) >= decision.desired:
                break
            try:
                self.store.create(self._build_replica(ms, i))
            except Exception:  # AlreadyExists under watch replay: benign
                log.debug("replica %s already exists", name, exc_info=True)
                continue
            created.append(name)
        if not created:
            return
        if cold_starting:
            self._record(
                ms,
                "ColdStart",
                f"cold start: {decision.reason}; created {len(created)} "
                f"replica(s) of {ms.spec.model}",
            )
        self._record(
            ms,
            "ScaledUp",
            f"{decision.reason}: replicas {len(live)} -> {decision.desired} "
            f"({ms.spec.slice_profile} x {len(created)} created)",
        )

    def _scale_down(
        self,
        ms: ModelServing,
        live: List[Pod],
        decision: policy.Decision,
        now: float,
    ) -> None:
        doomed = live[decision.desired :]  # highest indices first out
        freed_nodes = [p.spec.node_name for p in doomed]
        for p in doomed:
            try:
                self.store.delete("Pod", p.metadata.name, p.metadata.namespace)
            except NotFoundError:
                continue
        if decision.desired == 0:
            self._reserve_nodes(ms, freed_nodes, now)
            self._record(
                ms,
                "ScaledToZero",
                f"{decision.reason}: released {len(doomed)} replica(s), "
                f"{ms.spec.chips_per_replica * len(doomed)} chips held in "
                f"{ms.spec.cold_start_grace_seconds:.0f}s cold-start grace",
            )
        else:
            self._record(
                ms,
                "ScaledDown",
                f"{decision.reason}: replicas {len(live)} -> {decision.desired}",
            )

    def _update_status(
        self,
        ms: ModelServing,
        decision: policy.Decision,
        current: int,
        ready: int,
        now: float,
    ) -> None:
        pods = self.replica_pods(ms)
        live = [p for p in pods if p.metadata.deletion_timestamp is None]
        replicas = len(live)
        ready_now = sum(1 for p in live if p.spec.node_name)

        transition = decision.desired != ms.status.desired_replicas
        cold_start_since = ms.status.cold_start_since
        cold_starts = ms.status.cold_starts
        if decision.verdict == policy.VERDICT_COLD_START and transition:
            cold_start_since = now
            cold_starts += 1
        elif cold_start_since > 0 and ready_now > 0:
            metrics.AUTOSCALER_COLD_START_SECONDS.observe(now - cold_start_since)
            cold_start_since = 0.0

        if (
            not transition
            and ms.status.replicas == replicas
            and ms.status.ready_replicas == ready_now
            and ms.status.last_verdict == decision.verdict
            and ms.status.cold_start_since == cold_start_since
            and ms.status.cold_starts == cold_starts
        ):
            return

        def mutate(obj: ModelServing) -> None:
            obj.status.replicas = replicas
            obj.status.ready_replicas = ready_now
            obj.status.desired_replicas = decision.desired
            obj.status.last_verdict = decision.verdict
            if transition:
                obj.status.last_transition_t = now
            obj.status.cold_start_since = cold_start_since
            obj.status.cold_starts = cold_starts

        try:
            self.store.patch_merge(
                "ModelServing", ms.metadata.name, ms.metadata.namespace, mutate
            )
        except NotFoundError:
            pass

    # -------------------------------------------------------------- debug

    def debug_payload(self) -> dict:
        servings = {}
        for ms in self.store.list("ModelServing"):
            live = [
                p
                for p in self.replica_pods(ms)
                if p.metadata.deletion_timestamp is None
            ]
            servings[f"{ms.metadata.namespace}/{ms.metadata.name}"] = {
                "model": ms.spec.model,
                "slice_profile": ms.spec.slice_profile,
                "chips_per_replica": ms.spec.chips_per_replica,
                "bounds": [ms.spec.min_replicas, ms.spec.max_replicas],
                "replicas": len(live),
                "ready_replicas": sum(1 for p in live if p.spec.node_name),
                "desired_replicas": ms.status.desired_replicas,
                "last_verdict": ms.status.last_verdict,
                "cold_starts": ms.status.cold_starts,
            }
        return {"servings": servings, "signals": self.signals.payload()}


def pod_to_serving_requests(store: KubeStore, event) -> List[Request]:
    """Watch mapper: a replica pod event maps back to its ModelServing."""
    key = event.object.metadata.labels.get(labels.MODEL_SERVING_LABEL)
    if not key or "." not in key:
        return []
    ns, _, name = key.partition(".")
    return [Request(name=name, namespace=ns)]
