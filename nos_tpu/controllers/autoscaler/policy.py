"""The autoscaling decision function — pure, so tests, the chaos oracle,
and the controller all call the same code.

Scaling is driven by *measured* SLO burn (slo/engine.py's multi-window
burn rates) plus queue depth, not raw request counters:

  scale up       fast-window burn above threshold, or backlog above the
                 per-replica queue target — the SLO is being spent faster
                 than the error budget allows.
  scale down     sustained error-budget surplus: both burn windows low,
                 budget above the spec's surplus floor, backlog fits the
                 smaller fleet, and the fleet has been stable a while.
  scale to zero  min_replicas == 0 and no demand for the spec's idle
                 window. A standing SLO with zero traffic is vacuously
                 compliant and must NOT hold replicas alive.
  cold start     a scaled-to-zero model sees demand again.

All verdicts clamp to [min_replicas, max_replicas] and move by at most
one replica per decision (cold start excepted: it jumps straight to
max(1, min_replicas)) so a noisy signal cannot flap the fleet.
"""
from __future__ import annotations

from dataclasses import dataclass

from nos_tpu.api.config import AutoscalerConfig
from nos_tpu.api.v1alpha1.modelserving import ModelServingSpec
from nos_tpu.controllers.autoscaler.signals import Signals

VERDICT_HOLD = "hold"
VERDICT_SCALE_UP = "scale-up"
VERDICT_SCALE_DOWN = "scale-down"
VERDICT_SCALE_TO_ZERO = "scale-to-zero"
VERDICT_COLD_START = "cold-start"


@dataclass(frozen=True)
class Decision:
    desired: int
    verdict: str
    reason: str


def _clamp(n: int, spec: ModelServingSpec) -> int:
    return max(spec.min_replicas, min(spec.max_replicas, n))


def decide(
    spec: ModelServingSpec,
    current: int,
    sig: Signals,
    cfg: AutoscalerConfig,
    now: float,
    last_transition_t: float = 0.0,
) -> Decision:
    """Desired replica count for a ModelServing given its live signals.

    ``current`` is the number of existing (non-terminating) replica pods;
    ``last_transition_t`` the time desired last changed (anti-flap floor).
    """
    # One transition per distinct timestamp: a reconcile storm (watch
    # replays, or a bench stepping a frozen virtual clock) must not
    # ladder the fleet several steps on one observation.
    if last_transition_t > 0.0 and now <= last_transition_t:
        return Decision(current, VERDICT_HOLD, "transition taken at this instant")

    demand = sig.queue_depth > 0 or (
        now - sig.last_request_t <= cfg.recent_activity_seconds
    )

    if current == 0:
        if demand:
            target = _clamp(max(1, spec.min_replicas), spec)
            return Decision(
                target,
                VERDICT_COLD_START,
                f"demand while at zero (queue={sig.queue_depth})",
            )
        if spec.min_replicas > 0:
            return Decision(
                spec.min_replicas, VERDICT_SCALE_UP, "below min_replicas"
            )
        return Decision(0, VERDICT_HOLD, "no demand at zero")

    if current < spec.min_replicas:
        return Decision(
            spec.min_replicas, VERDICT_SCALE_UP, "below min_replicas"
        )

    if current < spec.max_replicas:
        if sig.burn_fast > cfg.scale_up_burn_threshold:
            return Decision(
                _clamp(current + 1, spec),
                VERDICT_SCALE_UP,
                f"fast burn {sig.burn_fast:.2f} > {cfg.scale_up_burn_threshold}",
            )
        if sig.queue_depth > current * spec.target_queue_depth:
            return Decision(
                _clamp(current + 1, spec),
                VERDICT_SCALE_UP,
                f"backlog {sig.queue_depth} > "
                f"{current} x {spec.target_queue_depth}",
            )

    idle_since = max(sig.last_request_t, last_transition_t)
    if (
        spec.min_replicas == 0
        and not demand
        and now - idle_since >= spec.scale_to_zero_idle_seconds
    ):
        return Decision(
            0,
            VERDICT_SCALE_TO_ZERO,
            f"idle {now - idle_since:.0f}s >= {spec.scale_to_zero_idle_seconds:.0f}s",
        )

    floor = max(1, spec.min_replicas)
    if (
        current > floor
        and sig.burn_fast < cfg.scale_down_burn_threshold
        and sig.burn_slow < cfg.scale_down_burn_threshold
        and sig.error_budget_remaining >= spec.scale_down_budget_surplus
        and sig.queue_depth <= (current - 1) * spec.target_queue_depth
        and now - last_transition_t >= cfg.scale_down_stable_seconds
    ):
        return Decision(
            current - 1,
            VERDICT_SCALE_DOWN,
            f"budget surplus {sig.error_budget_remaining:.2f} with "
            f"burn {sig.burn_fast:.2f}/{sig.burn_slow:.2f}",
        )

    return Decision(current, VERDICT_HOLD, "signals within band")
