"""SLO-driven model-serving autoscaler.

Closes the control-plane/data-plane loop (ROADMAP item 3): a ModelServing
CRD declares a model, the slice profile each replica occupies, replica
bounds, and SLO targets; the controller here reconciles desired replicas
from measured burn rate + queue depth and acts purely through the
API-server contract — it writes replica Pods, the scheduler gang-places
them, the partitioner carves the slices, ElasticQuota arbitrates.

  policy.py    pure decision function (spec + signals -> Decision)
  signals.py   thread-safe per-model signal registry fed by slo/ + routing
  controller.py  the ModelServing reconciler
"""
from nos_tpu.controllers.autoscaler.controller import ModelServingReconciler
from nos_tpu.controllers.autoscaler.policy import (
    Decision,
    VERDICT_COLD_START,
    VERDICT_HOLD,
    VERDICT_SCALE_DOWN,
    VERDICT_SCALE_TO_ZERO,
    VERDICT_SCALE_UP,
    decide,
)
from nos_tpu.controllers.autoscaler.signals import SignalRegistry, Signals

__all__ = [
    "Decision",
    "ModelServingReconciler",
    "SignalRegistry",
    "Signals",
    "VERDICT_COLD_START",
    "VERDICT_HOLD",
    "VERDICT_SCALE_DOWN",
    "VERDICT_SCALE_TO_ZERO",
    "VERDICT_SCALE_UP",
    "decide",
]
