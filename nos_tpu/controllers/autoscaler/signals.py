"""Per-model scaling signals, bridged from the data plane.

The SLO engine and the serving router run on their own clocks (wall time
live, the virtual cost clock in benches), so the registry takes an
injectable ``now_fn`` and the controller reads ALL its timestamps through
it — bench runs stay bit-stable because no wall-clock value ever reaches
a decision or a status field.

Writers:
  slo/ evaluation   burn_fast / burn_slow / error_budget_remaining
  routing shim      queue_depth / last_request_t (arrivals, backlog)
Reader: the ModelServing reconciler, via ``get(model)``.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace
from typing import Callable, Dict


@dataclass(frozen=True)
class Signals:
    # Max burn rate across the model's SLOs per window; min budget left.
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    error_budget_remaining: float = 1.0
    # Requests accepted by the router but not yet submitted to a replica.
    queue_depth: int = 0
    # When the model last saw an arrival; -inf = never.
    last_request_t: float = float("-inf")


class SignalRegistry:
    def __init__(self, now_fn: Callable[[], float] = time.time) -> None:
        self.now_fn = now_fn
        self._lock = threading.Lock()
        self._by_model: Dict[str, Signals] = {}

    def now(self) -> float:
        return self.now_fn()

    def get(self, model: str) -> Signals:
        with self._lock:
            return self._by_model.get(model, Signals())

    def update(self, model: str, **fields) -> Signals:
        """Replace the named fields of the model's signals atomically."""
        with self._lock:
            sig = replace(self._by_model.get(model, Signals()), **fields)
            self._by_model[model] = sig
            return sig

    def note_arrival(self, model: str, t: float, queue_depth: int) -> None:
        with self._lock:
            sig = self._by_model.get(model, Signals())
            self._by_model[model] = replace(
                sig,
                last_request_t=max(sig.last_request_t, t),
                queue_depth=queue_depth,
            )

    def models(self):
        with self._lock:
            return sorted(self._by_model)

    def payload(self) -> Dict[str, dict]:
        """/debug/autoscaler building block: every model's current signals."""
        with self._lock:
            return {
                m: {
                    "burn_fast": s.burn_fast,
                    "burn_slow": s.burn_slow,
                    "error_budget_remaining": s.error_budget_remaining,
                    "queue_depth": s.queue_depth,
                    "last_request_t": s.last_request_t,
                }
                for m, s in sorted(self._by_model.items())
            }
