"""TpuActuator: spec annotations → device create/delete + plugin restart.

Reference internal/controllers/migagent/actuator.go:71-292: on node
annotation change, wait for ≥1 report since last apply, parse spec vs
status, compute the declarative plan, execute deletes then creates, and
restart the device plugin when devices changed.
"""
from __future__ import annotations

import logging
from typing import Optional, Protocol

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.controllers.tpuagent.plan import compute_plan
from nos_tpu.controllers.tpuagent.shared import SharedState
from nos_tpu.device.client import TpuClient
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.util import metrics

log = logging.getLogger("nos_tpu.tpuagent")


class DevicePlugin(Protocol):
    def restart(self, node_name: str) -> None: ...


class TpuActuator:
    def __init__(
        self,
        store: KubeStore,
        client: TpuClient,
        device_plugin: DevicePlugin,
        node_name: str,
        shared: SharedState,
    ) -> None:
        self.store = store
        self.client = client
        self.device_plugin = device_plugin
        self.node_name = node_name
        self.shared = shared

    def reconcile(self, req: Request) -> Optional[Result]:
        if req.name != self.node_name:
            return None
        if not self.shared.at_least_one_report_since_last_apply():
            # Never act on device state older than the last apply
            # (actuator.go:75-78).
            return Result(requeue_after=0.1)
        try:
            node = self.store.get("Node", self.node_name)
        except NotFoundError:
            return None

        spec, _ = annot.parse_node_annotations(node.metadata.annotations)
        plan_id = node.metadata.annotations.get(annot.SPEC_PARTITIONING_PLAN, "")
        devices = self.client.get_devices(self.node_name)
        desired = annot.spec_geometries(spec)
        plan = compute_plan(devices, desired)
        if plan.empty:
            self.shared.on_apply(plan_id)
            return None

        for device in plan.deletes:
            self.client.delete_slice(self.node_name, device.device_id)
            metrics.SLICES_DELETED.inc()
            log.info("actuator: %s deleted %s", self.node_name, device.device_id)
        creates_by_board: dict = {}
        for op in plan.creates:
            board = creates_by_board.setdefault(op.board_index, {})
            board[op.profile] = board.get(op.profile, 0) + op.quantity
        for board_index, profiles in sorted(creates_by_board.items()):
            # One batch per board: chip-placement-aware backends solve all
            # of a board's creates together (order-independent).
            self.client.create_slices_batch(self.node_name, board_index, profiles)
            metrics.SLICES_CREATED.inc(sum(profiles.values()))
            log.info(
                "actuator: %s created %s on board %d",
                self.node_name,
                profiles,
                board_index,
            )
        self.device_plugin.restart(self.node_name)
        self.shared.on_apply(plan_id)
        return None
