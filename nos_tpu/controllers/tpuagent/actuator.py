"""TpuActuator: spec annotations → device create/delete + plugin restart.

Reference internal/controllers/migagent/actuator.go:71-292: on node
annotation change, wait for ≥1 report since last apply, parse spec vs
status, compute the declarative plan, execute deletes then creates, and
restart the device plugin when devices changed.
"""
from __future__ import annotations

import contextlib
import logging
from typing import Optional, Protocol

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.controllers.tpuagent.plan import compute_plan
from nos_tpu.controllers.tpuagent.shared import SharedState
from nos_tpu.device.client import TpuClient
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.store import KubeStore, NotFoundError
from nos_tpu.util import metrics
from nos_tpu.util.tracing import NOOP_SPAN, TRACER

log = logging.getLogger("nos_tpu.tpuagent")


class DevicePlugin(Protocol):
    def restart(self, node_name: str) -> None: ...


class TpuActuator:
    def __init__(
        self,
        store: KubeStore,
        client: TpuClient,
        device_plugin: DevicePlugin,
        node_name: str,
        shared: SharedState,
    ) -> None:
        self.store = store
        self.client = client
        self.device_plugin = device_plugin
        self.node_name = node_name
        self.shared = shared
        # Clamp-log throttle: (plan_id, board, profile) keys already logged
        # at error level; repeats (same stale spec re-reconciled until the
        # control plane replans) drop to debug. Reset on plan-id change.
        self._clamp_logged: set = set()
        # Chaos seam: callable(node_name, stage) armed only by the chaos
        # harness; raising from it models the agent process dying
        # mid-actuation (devices already mutated, apply never acked).
        self.chaos_interrupt = None

    def _chaos_point(self, stage: str) -> None:
        hook = self.chaos_interrupt
        if hook is not None:
            hook(self.node_name, stage)

    def reconcile(self, req: Request) -> Optional[Result]:
        if req.name != self.node_name:
            return None
        if not self.shared.at_least_one_report_since_last_apply():
            # Never act on device state older than the last apply
            # (actuator.go:75-78).
            return Result(requeue_after=0.1)
        try:
            node = self.store.get("Node", self.node_name)
        except NotFoundError:
            return None

        spec, _ = annot.parse_node_annotations(node.metadata.annotations)
        plan_id = node.metadata.annotations.get(annot.SPEC_PARTITIONING_PLAN, "")
        devices = self.client.get_devices(self.node_name)
        desired = annot.spec_geometries(spec)
        plan = compute_plan(devices, desired)
        if plan.empty:
            self.shared.on_apply(plan_id)
            return None

        # The control plane's actuator linked the apply span under
        # ("reconfig", node, plan_id); parenting on it stitches this
        # agent-side reconfig into the originating pod's trace. No link
        # (agent-only tests, repeat reconciles of the same plan): no span.
        parent = TRACER.linked(("reconfig", self.node_name, plan_id))
        ctx = (
            TRACER.span(
                "tpuagent.reconfig", parent=parent,
                node=self.node_name, plan_id=plan_id,
            )
            if parent is not None
            else contextlib.nullcontext(NOOP_SPAN)
        )
        with ctx as span:
            for device in plan.deletes:
                self.client.delete_slice(self.node_name, device.device_id)
                metrics.SLICES_DELETED.labels(profile=device.profile).inc()
                log.info("actuator: %s deleted %s", self.node_name, device.device_id)
            # The window where a real agent crash hurts most: deletes are
            # on the silicon but the creates/ack are not.
            self._chaos_point("post-delete")
            creates_by_board: dict = {}
            for op in plan.creates:
                board = creates_by_board.setdefault(op.board_index, {})
                board[op.profile] = board.get(op.profile, 0) + op.quantity
            self._clamp_to_board_capacity(node, plan, plan_id, creates_by_board)
            if not plan.deletes and not creates_by_board:
                # The whole plan was clamped away: spec is infeasible against
                # current device state. Nothing changed on the node, so do NOT
                # restart the device plugin; acknowledge the plan (the reporter
                # will publish the true geometry, and the partitioner's
                # divergence watch replans from it).
                span.set_attributes(clamped=True)
                self.shared.on_apply(plan_id)
                return None
            created = 0
            for board_index, profiles in sorted(creates_by_board.items()):
                # One batch per board: chip-placement-aware backends solve all
                # of a board's creates together (order-independent).
                self.client.create_slices_batch(self.node_name, board_index, profiles)
                for profile, qty in profiles.items():
                    metrics.SLICES_CREATED.labels(profile=profile).inc(qty)
                    created += qty
                log.info(
                    "actuator: %s created %s on board %d",
                    self.node_name,
                    profiles,
                    board_index,
                )
            span.set_attributes(deleted=len(plan.deletes), created=created)
            # Devices fully reshaped but the apply not yet acknowledged:
            # a crash here leaves the reporter republishing the new
            # geometry while the spec plan is never marked applied.
            self._chaos_point("pre-report")
            self.device_plugin.restart(self.node_name)
            self.shared.on_apply(plan_id)
        return None

    def _clamp_to_board_capacity(
        self, node, plan, plan_id: str, creates_by_board: dict
    ) -> None:
        """Refuse creates that would exceed a board's physical chips.

        The control plane can ask for an impossible geometry when it planned
        against state that lagged a recent bind (its spec plus still-used
        slices exceeding the board). Real silicon rejects such placements at
        device-creation; mirror that here so an inflated geometry is never
        advertised, and let the level-triggered loop re-converge from the
        next report. Reference analogue: NVML creation failures in
        migagent's apply, which are logged and re-reconciled.
        """
        from nos_tpu.api.v1alpha1 import constants, labels
        from nos_tpu.tpu.known import board_layout
        from nos_tpu.tpu.topology import Topology

        accelerator = node.metadata.labels.get(labels.GKE_TPU_ACCELERATOR_LABEL, "")
        chips = int(node.status.capacity.get(constants.RESOURCE_TPU, 0))
        layouts = board_layout(accelerator, chips)
        if not layouts:
            return
        deleted_ids = {d.device_id for d in plan.deletes}
        surviving: dict = {}
        for device in self.client.get_devices(self.node_name):
            if device.device_id not in deleted_ids:
                surviving[device.board_index] = surviving.get(
                    device.board_index, 0
                ) + Topology(device.profile).chips
        for board_index, profiles in sorted(creates_by_board.items()):
            if board_index >= len(layouts):
                log.error(
                    "actuator: %s spec references board %d beyond layout %s; "
                    "dropping its creates",
                    self.node_name,
                    board_index,
                    layouts,
                )
                profiles.clear()
                continue
            budget = Topology(layouts[board_index]).chips - surviving.get(
                board_index, 0
            )
            for profile in sorted(profiles):
                per = Topology(profile).chips
                fit = max(0, min(profiles[profile], budget // per))
                if fit < profiles[profile]:
                    clamp_key = (plan_id, board_index, profile)
                    if {k[0] for k in self._clamp_logged} - {plan_id}:
                        self._clamp_logged = {
                            k for k in self._clamp_logged if k[0] == plan_id
                        }
                    level = (
                        log.debug
                        if clamp_key in self._clamp_logged
                        else log.error
                    )
                    self._clamp_logged.add(clamp_key)
                    level(
                        "actuator: %s board %d: spec wants %dx %s but only "
                        "%d chips remain; clamping to %d (stale plan, will "
                        "re-converge)",
                        self.node_name,
                        board_index,
                        profiles[profile],
                        profile,
                        budget,
                        fit,
                    )
                    profiles[profile] = fit
                budget -= fit * per
            for profile in [p for p, q in profiles.items() if q <= 0]:
                del profiles[profile]
        for board_index in [b for b, p in creates_by_board.items() if not p]:
            del creates_by_board[board_index]
