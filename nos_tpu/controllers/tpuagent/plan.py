"""Declarative slice-plan diff (reference internal/controllers/migagent/plan/plan.go:31-92).

Given the devices that exist and the spec geometries the control plane
wants, produce delete and create operations. Deletes run before creates
(actuator.go:152-200). Used devices are never deleted — the planner never
plans away used slices (gpu.go UpdateGeometryFor preserves them), so a diff
demanding it means stale state; we skip and let the level-triggered loop
retry after the next report.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from nos_tpu.device.types import DeviceStatus, TpuSliceDevice


@dataclass
class CreateOp:
    board_index: int
    profile: str
    quantity: int


@dataclass
class SlicePlan:
    deletes: List[TpuSliceDevice] = field(default_factory=list)
    creates: List[CreateOp] = field(default_factory=list)

    @property
    def empty(self) -> bool:
        return not self.deletes and not self.creates


def compute_plan(
    devices: List[TpuSliceDevice], spec: Dict[int, Dict[str, int]]
) -> SlicePlan:
    existing: Dict[Tuple[int, str], List[TpuSliceDevice]] = {}
    for d in devices:
        existing.setdefault((d.board_index, d.profile), []).append(d)

    plan = SlicePlan()
    # Deletes: devices over spec quantity (or of profiles absent from spec).
    for (board, profile), devs in sorted(existing.items()):
        want = spec.get(board, {}).get(profile, 0)
        excess = len(devs) - want
        if excess <= 0:
            continue
        free = sorted(
            (d for d in devs if d.status == DeviceStatus.FREE), key=lambda d: d.device_id
        )
        plan.deletes.extend(free[:excess])
        # excess beyond free devices would require deleting used slices —
        # refused; the remaining diff re-converges after pods finish.

    # Creates: spec quantity beyond existing.
    for board in sorted(spec):
        for profile in sorted(spec[board]):
            want = spec[board][profile]
            have = len(existing.get((board, profile), []))
            if want > have:
                plan.creates.append(CreateOp(board, profile, want - have))
    return plan
