"""TpuReporter: device state → status annotations.

Reference internal/controllers/migagent/reporter.go:54-123: every report
interval (or on node change), read actual devices and write status-*
annotations; publish the plan id once the reported geometry matches spec,
completing the plan handshake that ungates the control-plane partitioner
(partitioner_controller.go:118-122, 212-232).
"""
from __future__ import annotations

import logging
from typing import Optional

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.device.client import TpuClient
from nos_tpu.device.types import group_geometries
from nos_tpu.controllers.tpuagent.shared import SharedState
from nos_tpu.kube.controller import Request, Result
from nos_tpu.kube.store import KubeStore, NotFoundError

log = logging.getLogger("nos_tpu.tpuagent")


class TpuReporter:
    def __init__(
        self,
        store: KubeStore,
        client: TpuClient,
        node_name: str,
        shared: SharedState,
        report_interval_seconds: float = 10.0,
    ) -> None:
        self.store = store
        self.client = client
        self.node_name = node_name
        self.shared = shared
        self.interval = report_interval_seconds

    def reconcile(self, req: Request) -> Optional[Result]:
        if req.name != self.node_name:
            return None
        try:
            node = self.store.get("Node", self.node_name)
        except NotFoundError:
            return None

        devices = self.client.get_devices(self.node_name)
        grouped = group_geometries(devices)
        desired_status = annot.status_from_devices(
            free=grouped["free"], used=grouped["used"]
        )

        spec, _ = annot.parse_node_annotations(node.metadata.annotations)
        spec_plan = node.metadata.annotations.get(annot.SPEC_PARTITIONING_PLAN, "")
        total = {
            board: geometry
            for board, geometry in _total_geometry(grouped).items()
            if geometry
        }
        if spec_plan and annot.spec_geometries(spec) == total:
            # Devices converged to spec: acknowledge the plan, ungating the
            # control-plane partitioner.
            desired_status[annot.STATUS_PARTITIONING_PLAN] = spec_plan
        elif spec_plan and self.shared.last_applied_plan_id == spec_plan:
            # The actuator finished acting on this plan but the result
            # diverges from spec (infeasible creates clamped). Withholding
            # the ack would wedge the plan gate until the spec happens to
            # become feasible — chips sit idle meanwhile. Acknowledge
            # instead: spec-plan == status-plan with geometry mismatch is
            # exactly the signal the partitioner's divergence watch
            # replans from.
            desired_status[annot.STATUS_PARTITIONING_PLAN] = spec_plan
        else:
            existing = node.metadata.annotations.get(annot.STATUS_PARTITIONING_PLAN)
            if existing is not None:
                desired_status[annot.STATUS_PARTITIONING_PLAN] = existing

        current_status = {
            k: v
            for k, v in node.metadata.annotations.items()
            if k.startswith(annot.PREFIX + "status-")
            # Hybrid nodes: sharing-profile entries belong to the
            # sharingagent; diffing them here would wipe its report.
            and not annot.is_sharing_status_key(k)
        }
        if current_status != desired_status:
            patch = {k: None for k in current_status}
            patch.update(desired_status)
            self.store.patch_annotations("Node", self.node_name, "", patch)
            log.info("reporter: %s status updated (%d devices)", self.node_name, len(devices))
        self.shared.on_report()
        return Result(requeue_after=self.interval)


def _total_geometry(grouped):
    out = {}
    for status_map in (grouped["free"], grouped["used"]):
        for board, geometry in status_map.items():
            target = out.setdefault(board, {})
            for profile, qty in geometry.items():
                target[profile] = target.get(profile, 0) + qty
    return out
