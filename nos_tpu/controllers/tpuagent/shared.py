"""Reporter/Actuator handshake (reference internal/controllers/migagent/shared.go:24-57):
the actuator refuses to act unless the reporter has observed the node since
the last apply, so it always diffs against fresh device state."""
from __future__ import annotations

import threading


class SharedState:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._reported_since_last_apply = False
        self.last_applied_plan_id = ""
        self._apply_listeners: list = []

    def add_apply_listener(self, fn) -> None:
        """fn(plan_id) runs after every apply — the agent wiring uses it to
        trigger an immediate report so the plan ack never waits out the
        report interval (critical for the no-op clamp path, which changes
        no devices and so generates no node event of its own)."""
        self._apply_listeners.append(fn)

    def on_report(self) -> None:
        with self._lock:
            self._reported_since_last_apply = True

    def on_apply(self, plan_id: str) -> None:
        with self._lock:
            self._reported_since_last_apply = False
            self.last_applied_plan_id = plan_id
        for fn in list(self._apply_listeners):
            fn(plan_id)

    def at_least_one_report_since_last_apply(self) -> bool:
        with self._lock:
            return self._reported_since_last_apply

    def reset(self) -> None:
        """Simulate the agent process restarting: all in-memory handshake
        state is lost (a fresh process has seen no report and remembers no
        applied plan). Listeners survive — they model the wiring, not the
        process."""
        with self._lock:
            self._reported_since_last_apply = False
            self.last_applied_plan_id = ""
