"""tpuagent: the node-local daemon (reference internal/controllers/migagent/).

Reporter publishes actual slice state as status annotations; Actuator turns
spec annotations into device create/delete calls through the TpuClient seam
and re-advertises resources via the device plugin. They coordinate through
SharedState so the actuator never acts before at least one fresh report.
"""

from nos_tpu.controllers.tpuagent.plan import SlicePlan, compute_plan
from nos_tpu.controllers.tpuagent.shared import SharedState
from nos_tpu.controllers.tpuagent.reporter import TpuReporter
from nos_tpu.controllers.tpuagent.actuator import TpuActuator

__all__ = ["SharedState", "SlicePlan", "TpuActuator", "TpuReporter", "compute_plan"]
