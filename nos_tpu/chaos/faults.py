"""Fault vocabulary, seeded schedule generation, and the injector.

Determinism contract: ``build_schedule(seed, ...)`` is a pure function —
the same arguments always produce the same bursts, faults, offsets and
workload pods (asserted by tests/chaos/test_faults.py). The injector's
per-write decisions use deterministic counters (every Nth eligible
operation faults) rather than a shared RNG, so the set of injected
faults depends only on each component's own operation sequence, not on
cross-thread RNG interleaving.
"""
from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from nos_tpu.kube.store import ConflictError
from nos_tpu.util import metrics

# Fault kinds. Backend-independent:
NODE_DEATH = "node-death"          # delete node + its pods, recreate at heal
NODE_CORDON_FLAP = "node-cordon-flap"  # spec.unschedulable True, then False
AGENT_RESTART = "agent-restart"    # kill tpuagent between apply and report
CONFLICT_WRITES = "conflict-writes"  # stale-rv ConflictError on store writes
QUOTA_FLAP = "quota-flap"          # ElasticQuota min collapses, then restores
LEADER_FLAP = "leader-flap"        # leader drops the lease mid-burst
CLOCK_SKEW = "clock-skew"          # wall clock runs ahead of monotonic
# Apiserver-backend only (the memory store has no HTTP surface):
WATCH_SEVER = "watch-sever"        # cut a watch stream mid-chunk
API_ERRORS = "api-errors"          # 503 bursts on API verbs
API_LATENCY = "api-latency"        # per-request added latency
# Opt-in only (never in ALL_KINDS: adding a kind to the sample pool
# would reshuffle every pinned seed's schedule). Armed by passing it
# through build_schedule's ``extra_kinds``; the driver enables it when
# the run's partitioner uses the process pool backend.
WORKER_KILL = "worker-kill"        # SIGKILL one pool-planner worker process

_HTTP_KINDS = (WATCH_SEVER, API_ERRORS, API_LATENCY)
ALL_KINDS = (
    NODE_DEATH,
    NODE_CORDON_FLAP,
    AGENT_RESTART,
    CONFLICT_WRITES,
    QUOTA_FLAP,
    LEADER_FLAP,
    CLOCK_SKEW,
) + _HTTP_KINDS


@dataclass
class Fault:
    kind: str
    target: str = ""   # node name for node faults; empty otherwise
    param: float = 0.0  # rate/budget/latency, kind-dependent
    at: float = 0.0     # seconds into the burst


@dataclass
class Burst:
    index: int
    duration_s: float
    faults: List[Fault] = field(default_factory=list)
    # Workload pods seeded just before the burst: (name, chips).
    pods: List[Tuple[str, int]] = field(default_factory=list)


def build_schedule(
    seed: int,
    bursts: int,
    nodes: List[str],
    backend: str = "memory",
    burst_s: float = 2.0,
    extra_kinds: Tuple[str, ...] = (),
) -> List[Burst]:
    """The seed's entire story, decided up front: which faults fire in
    which burst, against which node, at what offset, and which workload
    pods ride along. Pure — no clocks, no global RNG.

    ``extra_kinds`` appends opt-in kinds (e.g. WORKER_KILL) to the sample
    pool; with the default () every pinned seed's schedule is unchanged.
    """
    rng = random.Random(seed)
    kinds = [k for k in ALL_KINDS if backend == "apiserver" or k not in _HTTP_KINDS]
    kinds += [k for k in extra_kinds if k not in kinds]
    out: List[Burst] = []
    for index in range(bursts):
        burst = Burst(index=index, duration_s=burst_s)
        # 2-4 distinct fault kinds per burst.
        for kind in rng.sample(kinds, k=rng.randint(2, min(4, len(kinds)))):
            fault = Fault(
                kind=kind,
                at=round(rng.uniform(0.0, burst_s * 0.5), 3),
            )
            if kind in (NODE_DEATH, NODE_CORDON_FLAP, AGENT_RESTART):
                fault.target = rng.choice(nodes)
            if kind == CONFLICT_WRITES:
                fault.param = rng.choice([2, 3, 5])  # every Nth write
            if kind == API_ERRORS:
                fault.param = rng.choice([3, 5, 8])  # every Nth request
            if kind == API_LATENCY:
                fault.param = rng.choice([0.02, 0.05])
            if kind == WATCH_SEVER:
                fault.param = rng.randint(1, 3)  # streams to cut
            if kind == CLOCK_SKEW:
                # Seconds the wall clock jumps ahead. Small on purpose:
                # heal snaps wall time BACK, and integrators that skip
                # non-positive dt stall until true time catches up — the
                # dead zone must fit inside the convergence window.
                fault.param = rng.choice([0.5, 1.0, 2.0])
            burst.faults.append(fault)
        burst.faults.sort(key=lambda f: (f.at, f.kind))
        for p in range(rng.randint(2, 4)):
            burst.pods.append(
                (f"chaos-{seed}-b{index}-p{p}", rng.choice([1, 1, 2, 4, 8]))
            )
        out.append(burst)
    return out


class FaultInjector:
    """The armed half of the schedule: rate faults the driver switches on
    for a burst window and off at heal.

    Wired into two seams, both free when disarmed:

    - ``KubeStore.fault_injector`` calls :meth:`on_store_write` before
      every write verb (memory backend) — raising ConflictError models a
      stale-resourceVersion rejection.
    - ``StubApiServer.set_fault_injector`` consults :meth:`on_request`
      before every verb and :meth:`take_sever` before every watch chunk
      (apiserver backend).

    The driver's own writes (seeding, node resurrection, healing) wrap in
    :meth:`suspended` so injected faults never hit the harness itself.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._local = threading.local()
        self._conflict_every = 0
        self._error_every = 0
        self._latency_s = 0.0
        self._sever_budget = 0
        self._skew_s = 0.0
        self._writes = 0
        self._requests = 0
        self.counts: Dict[str, int] = {}

    # ------------------------------------------------------------- arming

    def arm_conflicts(self, every: int) -> None:
        with self._lock:
            self._conflict_every = int(every)

    def arm_errors(self, every: int) -> None:
        with self._lock:
            self._error_every = int(every)

    def arm_latency(self, seconds: float) -> None:
        with self._lock:
            self._latency_s = float(seconds)

    def arm_sever(self, budget: int) -> None:
        with self._lock:
            self._sever_budget += int(budget)

    def arm_clock_skew(self, seconds: float) -> None:
        with self._lock:
            self._skew_s = float(seconds)

    def skew_seconds(self) -> float:
        with self._lock:
            return self._skew_s

    def wall_clock(self) -> float:
        """``time.time`` plus the armed skew: components wired to this
        seam (the capacity ledger's heartbeat, lease renew stamps) see a
        wall clock that runs ahead of monotonic while armed, and snaps
        back at heal — monotonic-age logic must shrug both jumps off."""
        import time

        return time.time() + self.skew_seconds()

    def clear(self) -> None:
        with self._lock:
            self._conflict_every = 0
            self._error_every = 0
            self._latency_s = 0.0
            self._sever_budget = 0
            self._skew_s = 0.0

    def suspended(self):
        """Context manager: the calling thread's store writes bypass
        injection (driver-internal operations)."""
        injector = self

        class _Suspend:
            def __enter__(self_inner):
                injector._local.depth = getattr(injector._local, "depth", 0) + 1

            def __exit__(self_inner, *exc):
                injector._local.depth -= 1

        return _Suspend()

    def _count(self, kind: str) -> None:
        with self._lock:
            self.counts[kind] = self.counts.get(kind, 0) + 1
        metrics.CHAOS_FAULTS.labels(kind=kind).inc()

    def record(self, kind: str) -> None:
        """Count a driver-executed fault (node death, agent restart, ...)
        in the same ledger as the rate faults."""
        self._count(kind)

    # ------------------------------------------------------------- seams

    def on_store_write(self, kind: str, name: str) -> None:
        if getattr(self._local, "depth", 0) > 0:
            return
        if kind == "Event":
            # Telemetry, not decision input (not in RECORDED_KINDS): real
            # controllers post events fire-and-forget, so conflicting them
            # would model a failure mode that doesn't exist.
            return
        with self._lock:
            every = self._conflict_every
            if every <= 0:
                return
            self._writes += 1
            fire = self._writes % every == 0
        if fire:
            self._count(CONFLICT_WRITES)
            raise ConflictError(
                f"chaos: injected resource version conflict on {kind}/{name}"
            )

    def on_request(self, method: str, path: str) -> Optional[Tuple[int, str]]:
        import time

        with self._lock:
            latency = self._latency_s
            every = self._error_every
            if every > 0:
                self._requests += 1
                fire = self._requests % every == 0
            else:
                fire = False
        if latency > 0:
            self._count(API_LATENCY)
            time.sleep(latency)
        if fire:
            self._count(API_ERRORS)
            return (503, "ServiceUnavailable")
        return None

    def take_sever(self) -> bool:
        with self._lock:
            if self._sever_budget <= 0:
                return False
            self._sever_budget -= 1
        self._count(WATCH_SEVER)
        return True
