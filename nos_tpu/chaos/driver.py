"""ChaosDriver: run the full suite under a seeded fault schedule and
prove it heals.

Per burst: seed workload pods, fire the burst's faults along their
scheduled offsets, heal everything the schedule broke, then poll the
convergence oracles until they all pass or the deadline expires. After
the last burst the whole run's flight-recorder log is replayed offline —
zero drift and zero audit violations is itself an oracle. On any
failure, the ddmin minimizer (nos_tpu/chaos/minimize.py) shrinks the log
to a committable regression fixture.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from nos_tpu.api.config import (
    AutoscalerConfig,
    GpuPartitionerConfig,
    SchedulerConfig,
    TpuAgentConfig,
)
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.chaos import faults as F
from nos_tpu.chaos import oracles
from nos_tpu.chaos.faults import Burst, FaultInjector, build_schedule
from nos_tpu.kube.leaderelection import LeaderElector
from nos_tpu.kube.store import AlreadyExistsError, NotFoundError
from nos_tpu.util import metrics

log = logging.getLogger("nos_tpu.chaos")

LEASE_NAME = "chaos-leader-lease"
QUOTA_NAME = "chaos-quota"
QUOTA_NAMESPACE = "default"
MODEL_SERVING_NAME = "chaos-model"


@dataclass
class ChaosConfig:
    seed: int = 0
    bursts: int = 3
    nodes: int = 3
    backend: str = "memory"  # "memory" | "apiserver"
    burst_s: float = 2.0
    convergence_timeout_s: float = 30.0
    recorder_capacity: int = 65536
    minimize: bool = True
    fixtures_dir: str = ""  # minimized repro lands here on failure
    export_path: str = ""   # full log always exported here when set
    # Pool plan execution backend for the partitioner under test ("" =
    # config default, i.e. serial in-parent). "process" spawns one
    # long-lived planner worker per pool AND arms the worker-kill fault:
    # the schedule may SIGKILL a live worker mid-run, and the burst still
    # has to converge through the escalate-to-in-parent + respawn path.
    pool_backend: str = ""


@dataclass
class BurstResult:
    index: int
    faults: List[str]
    converged: bool
    convergence_s: float
    violations: List[str] = field(default_factory=list)


@dataclass
class ChaosReport:
    seed: int
    backend: str
    bursts: List[BurstResult] = field(default_factory=list)
    replay_ok: bool = True
    replay_summary: str = ""
    fault_counts: Dict[str, int] = field(default_factory=dict)
    fixture_path: str = ""
    records: int = 0
    # timeline-clean oracle: leak/stall findings evaluated once after the
    # final heal (see oracles.timeline_clean for why not per burst).
    timeline_violations: List[str] = field(default_factory=list)

    def ok(self) -> bool:
        return (
            self.replay_ok
            and not self.timeline_violations
            and all(b.converged for b in self.bursts)
        )

    def render(self) -> str:
        lines = [
            f"chaos seed={self.seed} backend={self.backend}: "
            f"{len(self.bursts)} burst(s), faults={self.fault_counts}"
        ]
        for b in self.bursts:
            status = (
                f"converged in {b.convergence_s:.2f}s"
                if b.converged
                else f"FAILED to converge ({len(b.violations)} violation(s))"
            )
            lines.append(f"  burst {b.index} [{', '.join(b.faults)}]: {status}")
            for v in b.violations[:8]:
                lines.append(f"    {v}")
        lines.append(
            f"  replay: {'clean' if self.replay_ok else 'FAILED'}"
            + (f" — {self.replay_summary}" if self.replay_summary else "")
        )
        lines.append(
            "  timeline: "
            + (
                "clean"
                if not self.timeline_violations
                else f"FAILED ({len(self.timeline_violations)} finding(s))"
            )
        )
        for v in self.timeline_violations[:8]:
            lines.append(f"    {v}")
        if self.fixture_path:
            lines.append(f"  minimized fixture: {self.fixture_path}")
        return "\n".join(lines)


class ChaosDriver:
    def __init__(self, config: Optional[ChaosConfig] = None) -> None:
        self.config = config or ChaosConfig()
        self.injector = FaultInjector()
        self.node_names = [f"chaos-node-{i}" for i in range(self.config.nodes)]
        self.schedule: List[Burst] = build_schedule(
            self.config.seed,
            self.config.bursts,
            self.node_names,
            backend=self.config.backend,
            burst_s=self.config.burst_s,
            extra_kinds=(
                (F.WORKER_KILL,)
                if self.config.pool_backend == "process"
                else ()
            ),
        )
        self._dead_nodes: Dict[str, object] = {}
        self.timeline = None
        self._cordoned: List[str] = []
        self._quota_flapped = False
        self._leader_overlap: List[str] = []

    # ------------------------------------------------------------ plumbing

    def _robust(self, fn, attempts: int = 8, delay: float = 0.05):
        """Driver-internal store operation: suspended from memory-backend
        injection, retried through apiserver-backend injected 503s (the
        HTTP seam cannot see the driver's thread-local suspension)."""
        last: Optional[Exception] = None
        for _ in range(attempts):
            try:
                with self.injector.suspended():
                    return fn()
            except (NotFoundError, AlreadyExistsError):
                raise
            except Exception as e:  # noqa: BLE001 — injected fault classes vary
                last = e
                time.sleep(delay)
        raise last  # type: ignore[misc]

    # -------------------------------------------------------------- setup

    def _build(self):
        from nos_tpu.cmd.cluster import build_cluster
        from nos_tpu.record import FlightRecorder

        self.recorder = FlightRecorder(
            capacity=self.config.recorder_capacity, seed=self.config.seed
        )
        self.api = None
        store = None
        if self.config.backend == "apiserver":
            from nos_tpu.kube.apiclient import ClusterCredentials, KubeApiClient
            from nos_tpu.kube.apistore import KubeApiStore
            from nos_tpu.sim.apiserver import StubApiServer

            self.api = StubApiServer().start()
            store = KubeApiStore(
                KubeApiClient(ClusterCredentials(server=self.api.url), timeout=5.0),
                relist_backoff_s=1.0,
                backoff_seed=self.config.seed,
            )
            store.start(sync_timeout_s=15.0)
        self.cluster = build_cluster(
            store=store,
            partitioner_config=GpuPartitionerConfig(
                batch_window_timeout_seconds=0.3,
                batch_window_idle_seconds=0.05,
                # Chaos inverts the production posture: threshold 1.0
                # forces EVERY base-preserving replan down the incremental
                # path (production falls back when too much is dirty; here
                # we want the riskiest path exercised as often as faults
                # allow), and the live auditor at full sample rate runs
                # the incremental-vs-from-scratch shadow check on each
                # one. The auditor_clean oracle fails the burst on any
                # recorded violation. (Tiny clusters — shadows are cheap.)
                incremental_planning=True,
                incremental_dirty_threshold=1.0,
                audit_sample_rate=1.0,
                # Sharded planning stays on under chaos too: the per-pool
                # shadow oracle (audit_sharded_plan) and the cross-pool
                # merge invariants must hold through every fault class,
                # and chaos pods carry no pool-pinning selectors so most
                # cycles exercise the mega-pool degradation as well.
                pool_sharding=True,
                # "" keeps the serial in-parent default; "process" puts
                # every pool plan behind the worker-process transport so
                # the schedule's worker-kill faults have something to
                # kill (and every other fault class crosses the process
                # boundary too).
                pool_backend=self.config.pool_backend,
                # Forecasting rides every chaos run: the background
                # forecaster keeps publishing ETAs through the faults and
                # the forecast-calibrated oracle (check_convergence)
                # re-forecasts the healed store — any gang still pending
                # despite a feasible-now verdict fails the burst. Tight
                # throttle so forecasts keep pace with 0.3s batch windows.
                forecast_enabled=True,
                forecast_min_interval_seconds=0.05,
            ),
            scheduler_config=SchedulerConfig(retry_seconds=0.1),
            # The model autoscaler rides every chaos run: its replica
            # fleet must survive node death / quota flaps / API faults and
            # re-settle to the decision function's verdict (the
            # autoscaler-settled oracle). Fast resync so idle-timer
            # reconciles land within the convergence window.
            autoscaler_config=AutoscalerConfig(resync_seconds=0.5),
            flight_recorder=self.recorder,
            timeline=self._build_timeline(),
        )
        self.store = self.cluster.store
        from nos_tpu.kube.events import EventRecorder
        from nos_tpu.kube.objects import ConfigMap, ObjectMeta

        self.timeline.attach(
            flight=self.recorder,
            recorder=EventRecorder(self.store, component="chaos-health-timeline"),
            event_obj=ConfigMap(
                metadata=ObjectMeta(name="nos-health-timeline", namespace="default")
            ),
        )
        # Clock-skew seam: the ledger's heartbeat observes against the
        # injector's wall clock, which runs ahead while the fault is
        # armed and snaps back at heal (observe skips non-positive dt, so
        # the snap-back stalls integration briefly instead of corrupting
        # it).
        if self.cluster.capacity_ledger is not None:
            self.cluster.capacity_ledger.wall_clock = self.injector.wall_clock
        # Arm the injection seams (both disarmed until a burst sets rates).
        if self.api is not None:
            self.api.set_fault_injector(self.injector)
        else:
            self.store.fault_injector = self.injector
        # Deltas from here on: nodes, quota, and all traffic get recorded.
        self.recorder.attach(self.store)
        agent_cfg = TpuAgentConfig(report_config_interval_seconds=0.3)
        from nos_tpu.cmd.run import seed_node

        for name in self.node_names:
            self.cluster.add_tpu_node(seed_node({"name": name}), agent_cfg)
        self._create_quota()
        self._create_modelserving()
        self._start_electors()
        self.cluster.start()

    def _build_timeline(self):
        """The soak's witness: 0.5s sampling against the 1.0s capacity
        heartbeat gives the stall detector (5 flat windows = 2.5s) a
        2.5x margin over the heartbeat period, so a healthy heartbeat
        can never read as wedged."""
        from nos_tpu.timeline import DetectorPolicy, TimelineStore

        self.timeline = TimelineStore(
            interval_seconds=0.5,
            policy=DetectorPolicy(
                stall_flat_windows=5,
                # The flight ring grows monotonically by design until its
                # deque bound; a "leak" on it is only real past capacity.
                leak_budgets={
                    "size.record.flight_ring": float(self.config.recorder_capacity)
                },
            ),
        )
        return self.timeline

    def _create_quota(self) -> None:
        from nos_tpu.api.v1alpha1.elasticquota import (
            ElasticQuota,
            ElasticQuotaSpec,
        )
        from nos_tpu.kube.objects import ObjectMeta

        chips = self.config.nodes * 8
        quota = ElasticQuota(
            metadata=ObjectMeta(name=QUOTA_NAME, namespace=QUOTA_NAMESPACE),
            spec=ElasticQuotaSpec(
                min={constants.RESOURCE_TPU: chips},
                max={constants.RESOURCE_TPU: chips},
            ),
        )
        self._robust(lambda: self.store.create(quota))

    def _create_modelserving(self) -> None:
        """One standing ModelServing: min 1 replica of a 2x2 slice. With
        no serve traffic its settled verdict is always "hold at
        min_replicas", so after every healed burst the oracle demands
        exactly one live replica pod — faults that evict it must be
        answered by a re-created replica."""
        from nos_tpu.api.v1alpha1.modelserving import (
            ModelServing,
            ModelServingSpec,
        )
        from nos_tpu.kube.objects import ObjectMeta

        ms = ModelServing(
            metadata=ObjectMeta(name=MODEL_SERVING_NAME, namespace=QUOTA_NAMESPACE),
            spec=ModelServingSpec(
                model=MODEL_SERVING_NAME,
                slice_profile="2x2",
                min_replicas=1,
                max_replicas=2,
                slos=["p95 ttft < 1s"],
            ),
        )
        self._robust(lambda: self.store.create(ms))

    def _start_electors(self) -> None:
        """Two contenders on a chaos-owned lease: the leader-flap fault
        drops the current holder; a monitor thread asserts mutual
        exclusion the whole run (two leaders at once is a failed oracle,
        whatever the fault mix did to the lease ConfigMap)."""
        self.electors = [
            LeaderElector(
                self.store,
                LEASE_NAME,
                identity,
                lease_duration_s=1.0,
                renew_period_s=0.2,
            )
            for identity in ("chaos-elector-a", "chaos-elector-b")
        ]
        # Clock-skew seam: ONE contender's renew stamps run on the skewed
        # wall clock — expiry is monotonic-age based, so mutual exclusion
        # (the monitor below) must survive divergent wall stamps.
        self.electors[0].wall_clock = self.injector.wall_clock
        self._monitor_stop = threading.Event()

        def monitor() -> None:
            while not self._monitor_stop.is_set():
                if all(e.is_leader for e in self.electors):
                    self._leader_overlap.append(
                        "leader-overlap: both contenders held the lease "
                        f"simultaneously at monotonic {time.monotonic():.3f}"
                    )
                time.sleep(0.005)

        self._monitor = threading.Thread(
            target=monitor, name="chaos-leader-monitor", daemon=True
        )
        for elector in self.electors:
            elector.start()
        self._monitor.start()

    # -------------------------------------------------------------- faults

    def _apply_fault(self, burst: Burst, fault: F.Fault) -> None:
        kind = fault.kind
        if kind == F.CONFLICT_WRITES:
            self.injector.arm_conflicts(int(fault.param))
        elif kind == F.API_ERRORS:
            self.injector.arm_errors(int(fault.param))
        elif kind == F.API_LATENCY:
            self.injector.arm_latency(fault.param)
        elif kind == F.WATCH_SEVER:
            self.injector.arm_sever(int(fault.param))
        elif kind == F.NODE_DEATH:
            self._kill_node(fault.target)
        elif kind == F.NODE_CORDON_FLAP:
            self._cordon(fault.target)
        elif kind == F.AGENT_RESTART:
            self._arm_agent_restart(burst, fault.target)
        elif kind == F.QUOTA_FLAP:
            self._flap_quota()
        elif kind == F.LEADER_FLAP:
            self._flap_leader()
        elif kind == F.CLOCK_SKEW:
            self.injector.arm_clock_skew(fault.param)
            self.injector.record(F.CLOCK_SKEW)
            log.info(
                "chaos: wall clock skewed %.1fs ahead of monotonic", fault.param
            )
        elif kind == F.WORKER_KILL:
            self._kill_worker()

    def _kill_worker(self) -> None:
        """Terminate one live pool-planner worker process WITHOUT telling
        its parent controller: the next plan cycle must notice the dead
        pipe itself, escalate that pool to in-parent planning, and
        respawn from a fresh wire image (partitioning/core/procpool.py).
        Workers spawn lazily on the first sharded cycle, so a kill that
        lands before any exist is a recorded no-op."""
        controllers = [self.cluster.partitioner]
        sharing = getattr(self.cluster.partitioner, "sharing", None)
        if sharing is not None:
            controllers.append(sharing)
        for controller in controllers:
            worker_pool = getattr(controller, "_worker_pool", None)
            if worker_pool is None:
                continue
            pool = worker_pool.chaos_kill_one()
            if pool is not None:
                self.injector.record(F.WORKER_KILL)
                log.info(
                    "chaos: killed %s pool worker for pool %s",
                    controller.kind,
                    pool,
                )
                return
        log.info("chaos: worker-kill fired with no live pool worker")

    def _kill_node(self, name: str) -> None:
        if name in self._dead_nodes:
            return
        node = self.store.try_get("Node", name)
        if node is None:
            return
        self._dead_nodes[name] = node
        # Eviction: pods on the node die with it.
        for pod in self.store.list("Pod"):
            if pod.spec.node_name == name:
                try:
                    self._robust(
                        lambda p=pod: self.store.delete(
                            "Pod", p.metadata.name, p.metadata.namespace
                        )
                    )
                except NotFoundError:
                    pass
        try:
            self._robust(lambda: self.store.delete("Node", name))
        except NotFoundError:
            pass
        self.injector.record(F.NODE_DEATH)
        log.info("chaos: killed node %s (and its pods)", name)

    def _resurrect_nodes(self) -> None:
        from nos_tpu.kube.objects import Node, NodeStatus, ObjectMeta

        for name, old in list(self._dead_nodes.items()):
            # A replaced machine comes back with labels and capacity but no
            # annotations: the reporter re-publishes geometry from device
            # state (which survived — slices persist across reboots) and
            # the partitioner replans the spec side.
            fresh = Node(
                metadata=ObjectMeta(
                    name=name, labels=dict(old.metadata.labels)
                ),
                status=NodeStatus(
                    capacity=dict(old.status.capacity),
                    allocatable=dict(old.status.allocatable),
                ),
            )
            try:
                self._robust(lambda n=fresh: self.store.create(n))
            except AlreadyExistsError:
                pass
            del self._dead_nodes[name]
            log.info("chaos: resurrected node %s", name)

    def _cordon(self, name: str) -> None:
        if name in self._dead_nodes:
            return

        def mutate(node) -> None:
            node.spec.unschedulable = True

        try:
            self._robust(lambda: self.store.patch_merge("Node", name, "", mutate))
        except NotFoundError:
            return
        self._cordoned.append(name)
        self.injector.record(F.NODE_CORDON_FLAP)
        log.info("chaos: cordoned node %s", name)

    def _uncordon_all(self) -> None:
        def mutate(node) -> None:
            node.spec.unschedulable = False

        for name in self._cordoned:
            try:
                self._robust(
                    lambda n=name: self.store.patch_merge("Node", n, "", mutate)
                )
            except NotFoundError:
                pass
        self._cordoned.clear()

    def _arm_agent_restart(self, burst: Burst, name: str) -> None:
        handles = self.cluster.agents.get(name)
        if handles is None:
            return
        # Interrupt stage alternates by burst so one seed exercises both
        # crash windows across its bursts.
        stage = "post-delete" if burst.index % 2 == 0 else "pre-report"
        injector = self.injector

        def interrupt(node_name: str, at_stage: str) -> None:
            if at_stage != stage:
                return
            # One-shot: disarm, lose the process's handshake memory, die.
            handles.actuator.chaos_interrupt = None
            handles.shared.reset()
            injector.record(F.AGENT_RESTART)
            log.info(
                "chaos: tpuagent on %s killed at %s (restart modeled by "
                "handshake reset)",
                node_name,
                at_stage,
            )
            raise RuntimeError(
                f"chaos: tpuagent on {node_name} died mid-actuation ({at_stage})"
            )

        handles.actuator.chaos_interrupt = interrupt

    def _flap_quota(self) -> None:
        def collapse(quota) -> None:
            quota.spec.min = {constants.RESOURCE_TPU: 0}
            quota.spec.max = {constants.RESOURCE_TPU: 1}

        try:
            self._robust(
                lambda: self.store.patch_merge(
                    "ElasticQuota", QUOTA_NAME, QUOTA_NAMESPACE, collapse
                )
            )
        except NotFoundError:
            return
        self._quota_flapped = True
        self.injector.record(F.QUOTA_FLAP)
        log.info("chaos: collapsed quota %s/%s", QUOTA_NAMESPACE, QUOTA_NAME)

    def _restore_quota(self) -> None:
        if not self._quota_flapped:
            return
        chips = self.config.nodes * 8

        def restore(quota) -> None:
            quota.spec.min = {constants.RESOURCE_TPU: chips}
            quota.spec.max = {constants.RESOURCE_TPU: chips}

        try:
            self._robust(
                lambda: self.store.patch_merge(
                    "ElasticQuota", QUOTA_NAME, QUOTA_NAMESPACE, restore
                )
            )
        except NotFoundError:
            pass
        self._quota_flapped = False

    def _flap_leader(self) -> None:
        for elector in self.electors:
            if elector.is_leader:
                elector.release()
                self.injector.record(F.LEADER_FLAP)
                log.info("chaos: dropped lease held by %s", elector.identity)
                return

    # --------------------------------------------------------------- run

    def _seed_pods(self, burst: Burst) -> None:
        from nos_tpu.cmd.run import seed_pod

        for name, chips in burst.pods:
            pod = seed_pod({"name": name, "chips": chips})
            try:
                self._robust(lambda p=pod: self.store.create(p))
            except AlreadyExistsError:
                pass

    def _cleanup_pods(self, burst: Burst) -> None:
        for name, _ in burst.pods:
            try:
                self._robust(
                    lambda n=name: self.store.delete("Pod", n, "default")
                )
            except NotFoundError:
                pass

    def _violations(self) -> List[str]:
        out = oracles.check_convergence(
            self.store,
            scheduler_name=self.cluster.scheduler.scheduler_name,
            partitioner=self.cluster.partitioner,
            autoscaler=self.cluster.autoscaler,
        )
        out += self._leader_overlap
        return out

    def _run_burst(self, burst: Burst) -> BurstResult:
        self._seed_pods(burst)
        start = time.monotonic()
        for fault in burst.faults:
            delay = start + fault.at - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            self._apply_fault(burst, fault)
        remaining = start + burst.duration_s - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)

        # Heal: rates off, nodes back, cordons lifted, quota restored.
        self.injector.clear()
        self._resurrect_nodes()
        self._uncordon_all()
        self._restore_quota()

        heal = time.monotonic()
        deadline = heal + self.config.convergence_timeout_s
        violations: List[str] = []
        while time.monotonic() < deadline:
            violations = self._violations()
            if not violations:
                break
            time.sleep(0.1)
        elapsed = time.monotonic() - heal
        converged = not violations
        if converged:
            metrics.CHAOS_CONVERGENCE.observe(elapsed)
        result = BurstResult(
            index=burst.index,
            faults=[f.kind for f in burst.faults],
            converged=converged,
            convergence_s=elapsed,
            violations=violations,
        )
        self._cleanup_pods(burst)
        return result

    def run(self) -> ChaosReport:
        report = ChaosReport(seed=self.config.seed, backend=self.config.backend)
        # Soak under the observability plane the ISSUE ships: a generous
        # default series budget (the soak's families must all fit — the
        # governor-clean oracle fails the run if any under-budget family
        # dropped) plus tight trace retention so the tail-kept reservoir
        # is what keeps error/slow traces through the churn.
        from nos_tpu.api.config import ObservabilityConfig
        from nos_tpu.obsplane.apply import apply_observability

        revert_observability = apply_observability(
            ObservabilityConfig(
                series_budget_default=512,
                trace_tail_capacity=32,
                trace_boring_sample_n=4,
            )
        )
        self._build()
        try:
            for burst in self.schedule:
                result = self._run_burst(burst)
                report.bursts.append(result)
                log.info(
                    "chaos: burst %d %s",
                    burst.index,
                    "converged" if result.converged else "FAILED",
                )
            # After the final heal: one last timeline sample, then the
            # timeline-clean oracle over the whole run's findings.
            self.timeline.tick()
            report.timeline_violations = oracles.timeline_clean(self.timeline)
            report.timeline_violations.extend(oracles.governor_clean())
        finally:
            self._monitor_stop.set()
            for elector in self.electors:
                elector.stop()
            self.cluster.stop()
            if self.timeline is not None:
                self.timeline.close()
            revert_observability()
            if self.config.backend == "apiserver":
                self.store.stop()
                self.api.stop()
            self.recorder.detach()

        records = self.recorder.records()
        report.records = len(records)
        report.fault_counts = dict(self.injector.counts)
        if self.config.export_path:
            self.recorder.export_jsonl(self.config.export_path)

        from nos_tpu.record.replay import ReplaySession

        replay = ReplaySession(records).run()
        report.replay_ok = replay.ok()
        if not replay.ok():
            report.replay_summary = replay.render().splitlines()[0]

        if not report.ok() and self.config.minimize and self.config.fixtures_dir:
            report.fixture_path = self._write_fixture(records)
        return report

    def _write_fixture(self, records: List[dict]) -> str:
        import json
        import os

        from nos_tpu.chaos.minimize import minimize_records, signature_names

        minimal, sig, probes = minimize_records(records)
        os.makedirs(self.config.fixtures_dir, exist_ok=True)
        # Filenames carry the oracle base names only; an empty signature
        # means a live-only failure (e.g. auditor against planner caches)
        # replay cannot reproduce — the full log is exported as 'full'.
        path = os.path.join(
            self.config.fixtures_dir,
            f"chaos-seed{self.config.seed}-"
            f"{'-'.join(signature_names(sig)) or 'full'}.jsonl",
        )
        with open(path, "w") as fh:
            for record in minimal:
                fh.write(json.dumps(record, sort_keys=True) + "\n")
        log.info(
            "chaos: minimized %d records to %d in %d probe(s) -> %s",
            len(records),
            len(minimal),
            probes,
            path,
        )
        return path
