"""ddmin over flight-recorder records: shrink a failing run to the
smallest record subset that still reproduces the same failure.

The probe re-runs the real replayer (record/replay.py) on the candidate
subset, then evaluates the store-state oracles on the replay-
reconstructed final state. A subset "fails the same way" when the set of
failing oracle names — plus replay-drift / audit-violation flags — is
EXACTLY the original signature; signature equality (not mere
non-emptiness) keeps the minimizer from wandering onto a different bug
than the one it was asked to isolate.

Classic Zeller/Hildebrandt delta debugging: split into n chunks, try
each chunk and each complement, recurse on the first reducer, double n
when nothing reduces. The ``session.start`` header is pinned (replay
needs it to rebuild the scheduler); everything else is fair game.
"""
from __future__ import annotations

from typing import Callable, FrozenSet, List, Tuple

from nos_tpu.chaos import oracles


def failure_signature(records: List[dict]) -> FrozenSet[str]:
    """Replay the records and name every way they fail: failing state
    oracles on the final replayed store, plus replay drift and audit
    violations. Empty = healthy."""
    from nos_tpu.record.replay import ReplaySession

    session = ReplaySession(records)
    try:
        report = session.run()
    except Exception:  # noqa: BLE001 — a crashing subset is its own signature
        return frozenset({"replay-crash"})
    session._apply_deltas_up_to(float("inf"))
    signature = set(
        oracles.failing_oracles(
            oracles.state_oracles(
                session.store,
                scheduler_name=session.meta.get("scheduler_name", ""),
            )
        )
    )
    for drift in report.drifts:
        # Pin each drifting record individually (seq survives subsetting:
        # replay reads the stored seq, never renumbers). Oracle-name
        # granularity alone lets ddmin wander onto a DIFFERENT degenerate
        # drift — e.g. strip every delta so some unrelated plan record
        # "drifts" against an empty store — and call it the same bug.
        signature.add(
            f"{oracles.REPLAY_CLEAN}@{drift.get('seq')}:{drift.get('kind', '')}"
        )
    if report.violations:
        signature.add(oracles.AUDITOR_CLEAN)
    return frozenset(signature)


def signature_names(signature: FrozenSet[str]) -> List[str]:
    """Collapse a signature to its oracle base names (sorted, unique) —
    the human-facing part fixture filenames and reports are built from."""
    return sorted({s.split("@", 1)[0] for s in signature})


def ddmin(
    records: List[dict],
    predicate: Callable[[List[dict]], bool],
    budget: int = 300,
) -> Tuple[List[dict], int]:
    """Minimize ``records`` (minus the pinned session header) under
    ``predicate`` (True = still fails the same way). Returns (minimal
    records including the header, probes spent). ``budget`` bounds probe
    count — on exhaustion the best reduction so far is returned."""
    pinned = [r for r in records if r.get("kind") == "session.start"]
    rest = [r for r in records if r.get("kind") != "session.start"]
    probes = 0

    def test(subset: List[dict]) -> bool:
        nonlocal probes
        probes += 1
        return predicate(pinned + subset)

    n = 2
    while len(rest) >= 2 and probes < budget:
        chunk = max(1, (len(rest) + n - 1) // n)
        subsets = [rest[i : i + chunk] for i in range(0, len(rest), chunk)]
        reduced = False
        for i, subset in enumerate(subsets):
            if probes >= budget:
                break
            if len(subset) < len(rest) and test(subset):
                rest = subset
                n = 2
                reduced = True
                break
            complement = [r for j, s in enumerate(subsets) for r in s if j != i]
            if probes >= budget:
                break
            if len(complement) < len(rest) and test(complement):
                rest = complement
                n = max(2, n - 1)
                reduced = True
                break
        if not reduced:
            if n >= len(rest):
                break
            n = min(len(rest), n * 2)
    return pinned + rest, probes


def minimize_records(
    records: List[dict], budget: int = 300
) -> Tuple[List[dict], FrozenSet[str], int]:
    """Compute the full run's failure signature, then ddmin to the
    smallest subset preserving it. Returns (minimal records, signature,
    probes). A healthy input returns itself untouched with an empty
    signature (nothing to minimize)."""
    target = failure_signature(records)
    if not target:
        return records, target, 0
    minimal, probes = ddmin(
        records, lambda subset: failure_signature(subset) == target, budget
    )
    return minimal, target, probes
