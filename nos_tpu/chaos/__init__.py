"""Deterministic chaos harness for the full suite.

``build_schedule`` turns a seed into a reproducible fault schedule;
``ChaosDriver`` runs the suite against it and asserts convergence after
every burst; ``minimize`` shrinks a failing run's flight-recorder log to
a minimal regression fixture.
"""
from nos_tpu.chaos.driver import ChaosConfig, ChaosDriver, ChaosReport
from nos_tpu.chaos.faults import Burst, Fault, FaultInjector, build_schedule
from nos_tpu.chaos.minimize import ddmin, failure_signature

__all__ = [
    "Burst",
    "ChaosConfig",
    "ChaosDriver",
    "ChaosReport",
    "Fault",
    "FaultInjector",
    "build_schedule",
    "ddmin",
    "failure_signature",
]
