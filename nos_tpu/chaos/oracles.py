"""Convergence oracles: what "the suite healed" means, as predicates.

Each oracle inspects store state only (so the minimizer can re-evaluate
them on a replay-reconstructed store); ``auditor_clean`` additionally
needs the live planner. The driver polls :func:`check_convergence` after
every burst until it returns no violations or the deadline passes.
"""
from __future__ import annotations

from typing import List

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import labels
from nos_tpu.kube.objects import PodPhase

# Oracle names — the minimizer's failure signatures are sets of these.
PENDING_SETTLED = "pending-settled"
ACTUATION_CONVERGED = "actuation-converged"
NO_ORPHANED_RESERVATIONS = "no-orphaned-reservations"
AUDITOR_CLEAN = "auditor-clean"
REPLAY_CLEAN = "replay-clean"
LEDGER_CONSISTENT = "ledger-consistent"
AUTOSCALER_SETTLED = "autoscaler-settled"
FORECAST_CALIBRATED = "forecast-calibrated"
TIMELINE_CLEAN = "timeline-clean"
GOVERNOR_CLEAN = "governor-clean"


def pending_settled(store, scheduler_name: str = "") -> List[str]:
    """Every pending pod of ours is either bound or carries a fresh
    scheduler verdict (PodScheduled=False/Unschedulable — the Diagnosis
    companion): no pod is ever silently stuck."""
    out: List[str] = []
    for pod in store.list("Pod"):
        if scheduler_name and pod.spec.scheduler_name != scheduler_name:
            continue
        if pod.status.phase != PodPhase.PENDING:
            continue
        if pod.spec.node_name:
            continue
        if not pod.unschedulable():
            out.append(
                f"{PENDING_SETTLED}: pod {pod.namespaced_name} is pending "
                "with neither a binding nor an Unschedulable verdict"
            )
    return out


def actuation_converged(store) -> List[str]:
    """Every TPU/hybrid node whose spec carries a partitioning plan has
    actuated it: the status plan id acknowledges the spec plan id and the
    reported geometry satisfies the spec geometry."""
    out: List[str] = []
    for node in store.list("Node"):
        if node.metadata.labels.get(labels.PARTITIONING_LABEL) not in (
            labels.PartitioningKind.TPU,
            labels.PartitioningKind.HYBRID,
        ):
            continue
        ann = node.metadata.annotations
        spec_plan = ann.get(annot.SPEC_PARTITIONING_PLAN, "")
        if not spec_plan:
            continue  # never planned: vacuously converged
        status_plan = ann.get(annot.STATUS_PARTITIONING_PLAN, "")
        name = node.metadata.name
        if status_plan != spec_plan:
            out.append(
                f"{ACTUATION_CONVERGED}: node {name} status plan "
                f"{status_plan!r} has not acknowledged spec plan {spec_plan!r}"
            )
            continue
        spec, status = annot.parse_node_annotations(ann)
        if not annot.spec_matches_status(spec, status):
            out.append(
                f"{ACTUATION_CONVERGED}: node {name} acked plan {spec_plan!r} "
                "but its reported geometry does not satisfy the spec"
            )
    return out


def no_orphaned_reservations(store) -> List[str]:
    """No node carries a board-reservation annotation whose holder is
    gone, bound, finished, or TTL-expired."""
    from nos_tpu.scheduler.plugins.reservation import RESERVED_FOR, BoardReservation

    checker = BoardReservation(store)
    out: List[str] = []
    for node in store.list("Node"):
        holder = node.metadata.annotations.get(RESERVED_FOR)
        if holder is None:
            continue
        if checker._valid_holder(node) is None:
            out.append(
                f"{NO_ORPHANED_RESERVATIONS}: node {node.metadata.name} is "
                f"reserved for {holder!r}, which is no longer a valid holder"
            )
    return out


def auditor_clean(partitioner, store) -> List[str]:
    """Exhaustive invariant audit of the live planner against a fresh
    snapshot (live-only: needs the planner's caches)."""
    from nos_tpu.partitioning.core.state import ClusterState
    from nos_tpu.record.audit import InvariantAuditor

    out: List[str] = []
    controllers = [("tpu", partitioner)]
    sharing = getattr(partitioner, "sharing", None)
    if sharing is not None:
        controllers.append(("sharing", sharing))
    for kind, controller in controllers:
        planner = getattr(controller, "planner", None)
        taker = getattr(controller, "snapshot_taker", None)
        if planner is None or taker is None:
            continue
        snapshot = taker.take_snapshot(ClusterState(), store=store)
        violations = InvariantAuditor(sample_rate=1.0).audit_plan(
            planner, snapshot, exhaustive=True, revision=store.revision
        )
        out.extend(
            f"{AUDITOR_CLEAN}: [{kind}] {v.check}: {v.detail}" for v in violations
        )
        # The controller's own live auditor (chaos runs it at full sample
        # rate) sees the plan inputs/outputs this oracle cannot — its
        # incremental-vs-from-scratch shadow check in particular. Any
        # violation it recorded during the run fails the oracle too.
        live = getattr(controller, "auditor", None)
        if live is not None and live.violations_total:
            out.append(
                f"{AUDITOR_CLEAN}: [{kind}] live auditor recorded "
                f"{live.violations_total} violation(s) during the run"
            )
    return out


def ledger_consistent(partitioner, store) -> List[str]:
    """The capacity ledger's incremental state matches a from-scratch
    recomputation off the store (live-only: needs the ledger). Quiesced
    polling makes the comparison non-racy: the driver calls this after a
    burst healed, when the store has stopped moving — a ledger observe is
    forced first so its watermark catches up to the settled store."""
    ledger = getattr(partitioner, "capacity_ledger", None)
    if ledger is None:
        return []
    import time

    # Recorded like any other observe: an unrecorded watermark advance
    # would make later recorded totals unreproducible on replay.
    ledger.observe(time.time())
    return [
        f"{LEDGER_CONSISTENT}: {diff}" for diff in ledger.self_check(store)
    ]


def forecast_calibrated(partitioner, store) -> List[str]:
    """After a burst heals, no gang the forecaster classified
    ``feasible-now`` may still be pending: a feasible-now forecast means
    the next plan/bind cycle places it, so a gang that stayed
    continuously feasible-now for several cycles without binding is a
    forecast the system contradicted (live-only: needs the forecaster).
    A fresh forecast runs first so the check reads the healed state, not
    a mid-burst stamp."""
    forecaster = getattr(partitioner, "forecaster", None)
    if forecaster is None:
        return []
    import time

    now = time.time()
    try:
        # The healed store's ACTUAL pending set, not the last notified
        # batch (whose pods may have bound or vanished since).
        pending = partitioner.fetch_pending_pods()
        forecaster.run_once(now=now, pending=pending)
    except Exception as exc:  # a crashed forecast fails the oracle too
        return [f"{FORECAST_CALIBRATED}: forecast run failed: {exc!r}"]
    return [
        f"{FORECAST_CALIBRATED}: gang {gang} forecast feasible-now has "
        "not bound within the cycle limit"
        for gang in forecaster.stale_feasible_now(now)
    ]


def autoscaler_settled(store, autoscaler) -> List[str]:
    """After a burst heals, every ModelServing's replica fleet is stable
    and MATCHES what the decision function says it should be: live pods ==
    status.desired_replicas == decide(...) at the controller's own clock,
    none terminating. Catches both a wedged reconciler (verdict never
    actuated) and a flapping one (actuation disagrees with the verdict a
    settled signal registry produces)."""
    from nos_tpu.controllers.autoscaler import policy
    from nos_tpu.controllers.autoscaler.controller import serving_key

    out: List[str] = []
    for ms in store.list("ModelServing"):
        key = serving_key(ms)
        pods = [
            p
            for p in store.list("Pod", namespace=ms.metadata.namespace)
            if p.metadata.labels.get(labels.MODEL_SERVING_LABEL) == key
        ]
        terminating = [p for p in pods if p.metadata.deletion_timestamp is not None]
        if terminating:
            out.append(
                f"{AUTOSCALER_SETTLED}: {key} still tearing down "
                f"{len(terminating)} replica(s)"
            )
            continue
        now = autoscaler.signals.now()
        decision = policy.decide(
            ms.spec,
            len(pods),
            autoscaler.signals.get(ms.spec.model),
            autoscaler.config,
            now,
            last_transition_t=ms.status.last_transition_t,
        )
        if decision.desired != len(pods):
            out.append(
                f"{AUTOSCALER_SETTLED}: {key} has {len(pods)} replica(s) but "
                f"the settled verdict is {decision.verdict} -> "
                f"{decision.desired} ({decision.reason})"
            )
        elif ms.status.desired_replicas != decision.desired:
            out.append(
                f"{AUTOSCALER_SETTLED}: {key} status.desired_replicas="
                f"{ms.status.desired_replicas} disagrees with the settled "
                f"verdict {decision.desired}"
            )
    return out


def timeline_clean(timeline) -> List[str]:
    """No leak or stall finding on the longitudinal health timeline
    (live-only: needs the TimelineStore). Regression findings are
    advisory under chaos — fault bursts legitimately slow replans — but a
    leak that kept growing or a loop that wedged is a real defect
    whatever the faults did. The driver evaluates this once, after the
    final heal: findings are cumulative (hysteresis only gates
    re-arming), so polling it per burst would deny convergence forever
    on the first transient."""
    from nos_tpu.timeline import detectors

    if timeline is None:
        return []
    out: List[str] = []
    for finding in timeline.findings():
        detector = finding.get("detector")
        if detector not in (detectors.LEAK, detectors.STALL):
            continue
        out.append(
            f"{TIMELINE_CLEAN}: {detector} on series "
            f"{finding.get('series')!r}: {finding.get('verdict')}"
        )
    return out


def governor_clean(registry=None) -> List[str]:
    """No under-budget metric family ever dropped a series (live-only:
    reads the cardinality governor's accounting). The governor is only
    allowed to fold label sets into ``_other`` once a family's exact
    series count has actually filled its budget; a drop on a family that
    never reached its budget — or one with no budget at all — means the
    admission accounting miscounted under the churn the faults caused."""
    from nos_tpu.util import metrics as metrics_mod

    registry = registry if registry is not None else metrics_mod.REGISTRY
    out: List[str] = []
    for name, fam in sorted(registry.series_report().items()):
        budget = fam.get("budget")
        if not fam["dropped"]:
            continue
        if budget is None or fam["exact"] < budget:
            out.append(
                f"{GOVERNOR_CLEAN}: family {name} dropped "
                f"{fam['dropped']} series while under budget "
                f"(exact={fam['exact']}, budget={budget})"
            )
    return out


def check_convergence(
    store,
    scheduler_name: str = "",
    partitioner=None,
    autoscaler=None,
) -> List[str]:
    """All oracles that can run mid-flight, concatenated. Empty = healed."""
    out = pending_settled(store, scheduler_name)
    out += actuation_converged(store)
    out += no_orphaned_reservations(store)
    if partitioner is not None:
        out += auditor_clean(partitioner, store)
        out += ledger_consistent(partitioner, store)
        out += forecast_calibrated(partitioner, store)
    if autoscaler is not None:
        out += autoscaler_settled(store, autoscaler)
    return out


def state_oracles(store, scheduler_name: str = "") -> List[str]:
    """The store-only subset — what the minimizer evaluates on a store
    rebuilt from recorded deltas (no live planner exists there)."""
    out = pending_settled(store, scheduler_name)
    out += actuation_converged(store)
    out += no_orphaned_reservations(store)
    return out


def failing_oracles(violations: List[str]) -> List[str]:
    """Collapse violation strings to their oracle names (sorted, unique) —
    the stable part a minimizer signature can match on."""
    return sorted({v.split(":", 1)[0] for v in violations})
