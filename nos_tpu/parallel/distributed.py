"""Multi-host distributed runtime bootstrap.

The bridge between the control plane's multi-host slice gangs
(nos_tpu/controllers/partitioner/multihost.py) and the workload's JAX
mesh: the expander stamps each gang member with its distributed
coordinates —

  NOS_TPU_COORDINATOR    host:port of process 0 (the gang leader)
  NOS_TPU_NUM_PROCESSES  gang size
  NOS_TPU_PROCESS_ID     this member's rank

— and the training container calls ``initialize()`` before touching any
device. After that, ``jax.devices()`` spans the whole ICI slice, and
``global_mesh`` lays the usual dp/sp/tp axes over it; everything in
nos_tpu/parallel (FSDP, ring attention, pipeline, MoE) works unchanged
because it is mesh-shape-agnostic.

On GKE multi-host TPU podslices, jax.distributed can also self-discover
through the TPU metadata server; the env coordinates take precedence when
present so the same image runs under both discovery modes.
"""
from __future__ import annotations

import logging
import os
from typing import Optional, Sequence, Tuple

logger = logging.getLogger("nos_tpu.distributed")

COORDINATOR_ENV = "NOS_TPU_COORDINATOR"
NUM_PROCESSES_ENV = "NOS_TPU_NUM_PROCESSES"
PROCESS_ID_ENV = "NOS_TPU_PROCESS_ID"
DEFAULT_COORDINATOR_PORT = 8476


def gang_member_env(leader: str, namespace: str, rank: int, size: int,
                    port: int = DEFAULT_COORDINATOR_PORT) -> dict:
    """The env block the expander stamps on gang member ``rank``.

    The coordinator address uses the leader pod's stable DNS name under a
    headless service named after the gang (create one per gang, or rely on
    GKE podslice discovery instead)."""
    return {
        COORDINATOR_ENV: f"{leader}.{leader}.{namespace}.svc:{port}",
        NUM_PROCESSES_ENV: str(size),
        PROCESS_ID_ENV: str(rank),
    }


def env_coordinates(environ=None) -> Optional[Tuple[str, int, int]]:
    """(coordinator, num_processes, process_id) from the env, or None when
    the gang coordinates are absent/incomplete."""
    environ = environ if environ is not None else os.environ
    coordinator = environ.get(COORDINATOR_ENV, "")
    try:
        num = int(environ.get(NUM_PROCESSES_ENV, ""))
        pid = int(environ.get(PROCESS_ID_ENV, ""))
    except ValueError:
        return None
    if not coordinator or num < 1 or not (0 <= pid < num):
        return None
    return coordinator, num, pid


def initialize(environ=None) -> bool:
    """Call ``jax.distributed.initialize`` from the gang coordinates.

    Returns True when a multi-process runtime was initialized, False for
    the single-process case (absent/size-1 coordinates) — callers can
    always invoke this unconditionally first thing in main()."""
    coords = env_coordinates(environ)
    if coords is None or coords[1] == 1:
        logger.info("distributed: single-process (no gang coordinates)")
        return False
    coordinator, num, pid = coords
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num,
        process_id=pid,
    )
    logger.info(
        "distributed: initialized as process %d/%d (coordinator %s)",
        pid, num, coordinator,
    )
    return True


def global_mesh(axis_shape: Sequence[int], axis_names: Sequence[str]):
    """A Mesh over ALL processes' devices (call after ``initialize``)."""
    import jax

    from nos_tpu.parallel.mesh import mesh_from_devices

    return mesh_from_devices(tuple(axis_shape), tuple(axis_names), jax.devices())
