"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second of the two classic long-context strategies (the first,
K/V-rotation ring attention, lives in nos_tpu/parallel/ring_attention.py;
the reference has no model stack — SURVEY.md §5 maps its scale axis to
slice topology, and this is the workload-side counterpart).

Where the ring keeps queries resident and rotates K/V blocks in n-1
neighbor hops (`ppermute` riding contiguous ICI), Ulysses trades TWO
`all_to_all` collectives for zero rotation: scatter the head axis across
the ``sp`` devices while gathering the full sequence, run ordinary
causal attention per head group on the whole sequence, then invert the
exchange. Comm volume is O(S·H·hd/n) per device either way, but Ulysses
does it in 2 balanced collectives instead of n-1 dependent steps — the
better fit when n is large relative to the per-hop latency, or when the
single-chip flash kernel on a full sequence beats n accumulator merges.
The trade: each device must hold the FULL sequence for H/n heads, so
activation memory is O(S·H·hd/n) vs the ring's O(S/n·H·hd) — Ulysses
scales context by shrinking heads-per-device, the ring by shrinking
resident sequence.

Exact (no approximation): both paths produce dense-attention results to
float tolerance, pinned by tests against the same oracle as the ring.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
from jax.sharding import Mesh

from nos_tpu.parallel.ring_attention import _ring_shard_map


def _dense_causal(q, k, v, causal, window=None):
    """Grouped-query attention on a full local sequence — delegates to
    the model stack's single GQA einsum (llama.gqa_dense_attention), so
    masking/scaling fixes land once."""
    from nos_tpu.models.llama import _window_causal_mask, gqa_dense_attention

    mask = _window_causal_mask(q.shape[1], window) if causal else None
    return gqa_dense_attention(q, k, v, mask)


def _ulysses_local(q, k, v, axis_name, causal, use_flash, interpret, window=None):
    """Local block: heads scatter / sequence gather, full-sequence
    attention, inverse exchange. q [b, S/n, Hq_loc, hd]."""
    # Scatter heads (split axis 2 into n), gather sequence (concat axis 1):
    # -> [b, S, Hq_loc/n, hd]. One balanced all_to_all over the sp axis.
    q = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if use_flash:
        from nos_tpu.ops import flash_attention

        out = flash_attention(
            q, k, v, causal=causal, interpret=interpret, window=window
        )
    else:
        out = _dense_causal(q, k, v, causal, window)
    # Inverse: scatter sequence, gather heads -> [b, S/n, Hq_loc, hd].
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = "tp",
    attention: str = "dense",
    window: Optional[int] = None,
) -> jax.Array:
    """Exact attention with q/k/v [B, S, H, hd] sequence-sharded over
    ``axis_name``; same calling convention as ``ring_attention`` (returns
    [B, S, Hq·hd]). ``attention="flash"`` runs the Pallas kernel on the
    gathered full sequence — differentiable end to end (all_to_all and
    the kernel's custom_vjp both transpose cleanly).

    Constraints (raise, never silently mis-group): per-device Q and KV
    head counts must divide by the sp degree, and each head chunk must
    span whole GQA groups so query heads keep their own K/V.
    """
    from nos_tpu.ops.flash_attention import validate_window

    validate_window(causal, window)
    names = mesh.axis_names
    if axis_name not in names:
        raise ValueError(f"mesh {names} has no sequence axis {axis_name!r}")
    n = mesh.shape[axis_name]
    tp = mesh.shape[head_axis] if head_axis in names else 1
    hq, hkv = q.shape[2], k.shape[2]
    hq_loc, hkv_loc = hq // tp, hkv // tp
    if hq_loc % n or hkv_loc % n:
        raise ValueError(
            f"ulysses needs per-device head counts divisible by sp={n} "
            f"(q {hq_loc}, kv {hkv_loc}); use ring attention for this shape"
        )
    # (No separate GQA-group check needed: hq_loc % n == 0 and
    # hkv_loc % n == 0 already force every head chunk to span whole
    # groups — chunk size hq_loc/n is (hq/hkv) * hkv_loc/n.)
    interpret = jax.default_backend() == "cpu"
    local = partial(
        _ulysses_local,
        axis_name=axis_name,
        causal=causal,
        use_flash=attention == "flash",
        interpret=interpret,
        window=window,
    )
    wrapped, _ = _ring_shard_map(
        local, mesh, axis_name, batch_axis, head_axis, out_rank4=True
    )
    b, s = q.shape[0], q.shape[1]
    return wrapped(q, k, v).reshape(b, s, hq * q.shape[3])
