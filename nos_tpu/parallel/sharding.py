"""Sharding rules for the Llama model over a ('dp','tp') mesh.

Megatron-style tensor parallelism: attention q/k/v and mlp gate/up shard
their output (head / ff) dimension over tp, wo and w_down shard their
input dimension — each layer needs exactly one psum on the residual path,
which XLA inserts from these NamedShardings. Embedding/lm_head shard the
vocab dimension. The batch dimension shards over dp.

FSDP: every 2-D weight additionally shards its non-tp dimension over dp,
so parameters AND optimizer state live chip-count-fractionally (a
Llama-3-8B train state fits a v5e 4x4 slice, BASELINE config #5). XLA
turns the annotations into all-gather-on-use / reduce-scatter-on-grad —
the scaling-book recipe, no hand-written collectives. 1-D norm scales
stay replicated (bytes are negligible, gathering them is not worth a
collective).
"""
from __future__ import annotations

from typing import Any, Dict

from jax.sharding import Mesh, NamedSharding

from nos_tpu.models.llama import LlamaConfig


def _ns(mesh: Mesh, *spec) -> NamedSharding:
    # Axis names the mesh doesn't carry degrade to replication, so the same
    # sharding rules serve ('dp','tp'), ('dp','sp','tp'), ('dp','ep'), ...
    from nos_tpu.parallel.mesh import partition_spec

    return NamedSharding(mesh, partition_spec(mesh, *spec))


def llama_param_sharding(mesh: Mesh, config: LlamaConfig) -> Dict[str, Any]:
    layer = {
        "attn_norm": _ns(mesh),
        "wq": _ns(mesh, "dp", "tp"),
        "wk": _ns(mesh, "dp", "tp"),
        "wv": _ns(mesh, "dp", "tp"),
        "wo": _ns(mesh, "tp", "dp"),
        "mlp_norm": _ns(mesh),
    }
    if config.n_experts > 0:
        from nos_tpu.models.moe import moe_param_sharding

        layer["moe"] = moe_param_sharding(mesh, config.moe_config())
    else:
        layer["w_gate"] = _ns(mesh, "dp", "tp")
        layer["w_up"] = _ns(mesh, "dp", "tp")
        layer["w_down"] = _ns(mesh, "tp", "dp")
    tree = {
        "embed": _ns(mesh, "tp", "dp"),
        "final_norm": _ns(mesh),
        "layers": [dict(layer) for _ in range(config.n_layers)],
    }
    if not config.tie_embeddings:
        tree["lm_head"] = _ns(mesh, "dp", "tp")
    return tree


def llama_quantized_sharding(
    mesh: Mesh, config: LlamaConfig, bits: int = 8, group: int = 128
) -> Dict[str, Any]:
    """Sharding tree matching quantize_params' (bits=8) or
    quantize_params_int4's (bits=4, same ``group``) output: each
    quantized weight shards like its dense original, and its scales
    shard along the same axis as the output dimension (per-vocab-row for
    the embedding), so dequantization stays local — no collective touches
    the scales. Structure mirrors the quantized pytree (QuantizedLinear /
    QuantizedLinear4 / QuantizedEmbedding nodes whose leaves are
    NamedShardings — int4 aux ``group`` must match the quantizer's),
    which is exactly what ``jax.device_put(qparams, sharding_tree)``
    wants."""
    from nos_tpu.models.quantize import (
        QuantizedEmbedding,
        QuantizedLinear,
        QuantizedLinear4,
    )

    if bits == 8:
        def lin(in_axis, out_axis):
            return QuantizedLinear(
                q=_ns(mesh, in_axis, out_axis), scale=_ns(mesh, out_axis)
            )
    elif bits == 4:
        def lin(in_axis, out_axis):
            # q [G, group/2, out]: groups tile the contraction dim, so the
            # group axis shards like the dense weight's contraction axis
            # (rows within a group stay together — the packed nibble pair
            # lives in one byte); scale [G, out] shards alongside.
            return QuantizedLinear4(
                q=_ns(mesh, in_axis, None, out_axis),
                scale=_ns(mesh, in_axis, out_axis),
                group=group,
            )
    else:
        raise ValueError(f"bits must be 4 or 8, got {bits}")

    layer = {
        "attn_norm": _ns(mesh),
        "wq": lin("dp", "tp"),
        "wk": lin("dp", "tp"),
        "wv": lin("dp", "tp"),
        "wo": lin("tp", "dp"),
        "mlp_norm": _ns(mesh),
    }
    if config.n_experts > 0:
        from nos_tpu.models.quantize import QuantizedExpertStack

        def stack(mid_axis, out_axis):
            return QuantizedExpertStack(
                q=_ns(mesh, "ep", mid_axis, out_axis),
                scale=_ns(mesh, "ep", out_axis),
            )

        layer["moe"] = {
            "router": _ns(mesh),
            "w_gate": stack("dp", "tp"),
            "w_up": stack("dp", "tp"),
            "w_down": stack("tp", "dp"),
        }
    else:
        layer["w_gate"] = lin("dp", "tp")
        layer["w_up"] = lin("dp", "tp")
        layer["w_down"] = lin("tp", "dp")
    tree = {
        "embed": QuantizedEmbedding(q=_ns(mesh, "tp", "dp"), scale=_ns(mesh, "tp")),
        "final_norm": _ns(mesh),
        "layers": [dict(layer) for _ in range(config.n_layers)],
    }
    if not config.tie_embeddings:
        tree["lm_head"] = lin("dp", "tp")
    return tree


def llama_data_sharding(mesh: Mesh) -> NamedSharding:
    """Tokens [B, S]: batch over dp; sequence over sp when the mesh has it
    (ring attention consumes the same block distribution)."""
    if "sp" in mesh.axis_names:
        return _ns(mesh, "dp", "sp")
    return _ns(mesh, "dp", None)
