"""Checkpoint / resume for sharded training state.

The reference suite is stateless (SURVEY.md §5: all durable state lives in
the k8s API, "checkpoint/resume: none") — but the *workloads* this suite
schedules are preemptible by design: the CapacityScheduling plugin evicts
over-quota training pods, and the partitioner re-carves freed boards. A
first-class suite therefore ships the workload-side answer: save the
sharded train state to durable storage and restore it onto whatever slice
the pod lands on next — including a different topology (orbax reshards on
restore from the target shardings).

Built on orbax: async-capable, multi-host-aware, and restore-time
resharding comes from passing abstract arrays with the new NamedShardings.
"""
from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

TrainState = Tuple[Any, Any]  # (params, velocity), matching train.make_train_step


class Checkpointer:
    """Long-lived manager for a training loop: saves overlap compute (orbax
    serializes in the background), and the loop only blocks in
    ``wait()``/``close()`` — call close() (or use as a context manager) at
    exit or on the preemption signal."""

    def __init__(self, path: str, *, max_to_keep: Optional[int] = None) -> None:
        self.path = os.path.abspath(path)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, enable_async_checkpointing=True
        )
        self._manager = ocp.CheckpointManager(self.path, options=options)

    def save(self, step: int, state: TrainState, *, force: bool = False) -> None:
        """Enqueue an async save; raises if orbax skips it (stale step)."""
        saved = self._manager.save(step, args=ocp.args.StandardSave(state), force=force)
        if not saved:
            raise RuntimeError(
                f"checkpoint save skipped for step {step} under {self.path} "
                f"(latest is {self._manager.latest_step()}; pass force=True)"
            )

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def restore(self, shard_like: TrainState, step: Optional[int] = None):
        if step is None:
            step = self._manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.path}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            shard_like,
        )
        return self._manager.restore(step, args=ocp.args.StandardRestore(abstract)), step

    def wait(self) -> None:
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def save_checkpoint(path: str, state: TrainState, step: int, *, force: bool = False) -> None:
    """One-shot synchronous save of `state` at `step` under path/<step>/
    (atomic rename on finish). Training loops should hold a `Checkpointer`
    instead so saves overlap compute.

    Raises if the manager skips the save (orbax silently refuses steps <=
    its latest unless forced — a dropped checkpoint must never be silent
    in a preempt-and-resume loop).
    """
    path = os.path.abspath(path)
    with ocp.CheckpointManager(path) as manager:
        saved = manager.save(step, args=ocp.args.StandardSave(state), force=force)
        manager.wait_until_finished()
        if not saved:
            raise RuntimeError(
                f"checkpoint save skipped for step {step} under {path} "
                f"(latest is {manager.latest_step()}; pass force=True to overwrite)"
            )


def latest_step(path: str) -> Optional[int]:
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return None
    with ocp.CheckpointManager(path) as manager:
        return manager.latest_step()


def restore_checkpoint(
    path: str, shard_like: TrainState, step: Optional[int] = None
) -> Tuple[TrainState, int]:
    """Restore (state, step) from path/<step>/, resharded to match
    `shard_like` — a state tree of (possibly abstract) arrays carrying the
    target mesh's NamedShardings, e.g. the output of
    ``make_train_step(new_mesh, ...)[1](params)`` or
    ``jax.eval_shape``+``jax.sharding`` equivalents. The restored arrays
    land directly in the new layout; no host-side gather.
    """
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        # Constructing the manager would create the directory as a side
        # effect, polluting durable storage on every failed resume.
        raise FileNotFoundError(f"no checkpoint under {path}")
    with ocp.CheckpointManager(path) as manager:
        if step is None:
            step = manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {path}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            shard_like,
        )
        state = manager.restore(step, args=ocp.args.StandardRestore(abstract))
        return state, step
