"""Device meshes from TPU slice topologies.

The bridge between the control plane's slice geometry and JAX's SPMD
model: a pod scheduled onto a carved slice builds its Mesh from the same
topology string the partitioner used, so data-parallel traffic rides the
slower mesh dimension and tensor-parallel collectives ride the contiguous
ICI dimension.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from nos_tpu.tpu.topology import Topology


def partition_spec(mesh: Mesh, *axes) -> PartitionSpec:
    """PartitionSpec over `axes` with names the mesh doesn't carry degraded
    to replication — one sharding rule serves every mesh shape."""
    return PartitionSpec(
        *(a if (a is None or a in mesh.axis_names) else None for a in axes)
    )


def mesh_from_devices(
    axis_shapes: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = int(np.prod(axis_shapes))
    if len(devices) < need:
        raise ValueError(f"need {need} devices for mesh {tuple(axis_shapes)}, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(tuple(axis_shapes))
    return Mesh(grid, tuple(axis_names))


def default_training_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """('dp','sp','tp') mesh over the available devices.

    tp takes the innermost (contiguous-ICI) position, sp the next ring, and
    the remainder folds into dp — the ordering that keeps tensor-parallel
    all-reduces and ring-attention neighbor exchanges on the fastest links.
    Axes that don't divide the device count collapse to 1.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    tp = 2 if n % 2 == 0 else 1
    rem = n // tp
    sp = 2 if rem % 2 == 0 else 1
    dp = rem // sp
    return mesh_from_devices((dp, sp, tp), ("dp", "sp", "tp"), devices)


def mesh_for_slice(
    topology: str,
    dp: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """('dp','tp') mesh covering one slice.

    Tensor parallelism wants the fastest all-reduce, so tp takes the last
    (contiguous) topology dimension; everything else folds into dp. An
    explicit `dp` overrides the split (dp·tp must equal the chip count).
    """
    t = Topology(topology)
    chips = t.chips
    if dp is None:
        tp = t.dims[-1]
        dp = chips // tp
    else:
        if chips % dp:
            raise ValueError(f"dp={dp} does not divide {chips} chips")
        tp = chips // dp
    return mesh_from_devices((dp, tp), ("dp", "tp"), devices)
