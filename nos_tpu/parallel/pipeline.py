"""Pipeline parallelism: GPipe schedule over a ``pp`` mesh axis.

The transformer stack is split into pp stages — layer parameters stack
along a leading dim sharded over ``pp``, so each device holds L/pp layers
in HBM (the memory win that lets one slice hold a model pp× its per-chip
capacity). The batch splits into microbatches that stream through the
stages: each tick every stage applies its local layers to the microbatch
it holds, then hands the activation to the next stage over a single
``ppermute`` hop (neighbor ICI traffic, never DCN). The schedule runs
M + pp - 1 ticks; the classic GPipe bubble is (pp-1)/(M+pp-1), shrinking
as microbatches grow.

Embedding, final norm, and the LM head are replicated outside the pipeline
body (they are a small fraction of parameters); only the repeated blocks
ride the pp axis. Differentiable end to end — the schedule unrolls into
static ticks of scan/ppermute/where, all with transpose rules.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nos_tpu.models.llama import (
    LlamaConfig,
    _attention,
    _embed_rows,
    _mlp,
    _mm,
    _rms_norm,
    _unembed_weight,
    _rope,
)

Params = Dict[str, Any]


def stack_layer_params(params: Params) -> Params:
    """[{leaf...}] * L → {leaf: [L, ...]} — the pp-shardable layout."""
    layers = params["layers"]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    return {**{k: v for k, v in params.items() if k != "layers"}, "layers": stacked}


def pipeline_param_sharding(mesh: Mesh, config: LlamaConfig) -> Params:
    """Stacked layers shard dim 0 over pp; the per-layer dims keep the
    dense rules — hidden over tp AND the FSDP dp shard, so each stage's
    resident layer slabs are further chip-count-fractional (ZeRO-style;
    the shard_map all-gathers them on use). embed/head replicate over pp
    like the dense rules."""
    from nos_tpu.parallel.sharding import llama_param_sharding

    base = llama_param_sharding(mesh, config)
    stacked_layers = jax.tree.map(
        lambda ns: NamedSharding(mesh, P("pp", *ns.spec)),
        base["layers"][0],
        is_leaf=lambda x: isinstance(x, NamedSharding),
    )
    return {
        **{k: v for k, v in base.items() if k != "layers"},
        "layers": stacked_layers,
    }


def _block(carry_x, layer: Params, config: LlamaConfig, cos, sin):
    """One transformer block on one stage (dense attention — sp/flash
    compose at the outer level, not inside the pipeline body). MoE layers
    run their routed FFN locally per stage (experts are stage-resident
    alongside the rest of the stacked layer; the balance aux loss is not
    threaded through the pipeline — add it as a separate regularizer if
    routing collapse matters for your run)."""
    x = carry_x
    x = x + _attention(
        _rms_norm(x, layer["attn_norm"], config.norm_eps, config.norm_offset),
        layer, config, cos, sin,
    )
    h = _rms_norm(x, layer["mlp_norm"], config.norm_eps, config.norm_offset)
    if "moe" in layer:
        from nos_tpu.models.moe import moe_mlp

        return x + moe_mlp(layer["moe"], h, config.moe_config(), None)
    return x + _mlp(h, layer, config.hidden_act)


def _stage_apply(local_layers: Params, x, config: LlamaConfig, cos, sin):
    """Apply this stage's L/pp stacked layers via scan."""

    def step(h, layer):
        return _block(h, layer, config, cos, sin), None

    out, _ = jax.lax.scan(step, x, local_layers)
    return out


def _pipeline_schedule(stacked_layers, x_mb, config: LlamaConfig, cos, sin, *, n_stages: int):
    """shard_map body over ('pp',): run the microbatch schedule.

    x_mb: [M, mb, S, D] microbatched activations (post-embedding),
    replicated — stage 0 ingests them in order. Returns [M, mb, S, D]
    activations after the full stack, VALID ONLY on the last stage (the
    caller decides whether to pay a collective to move them).

    With ``config.remat`` each tick's stage application is checkpointed:
    the backward replays one (microbatch × stage) block at a time, so live
    activation memory is bounded by the carries — the same O(pp) bound
    1F1B achieves by schedule order, obtained here by rematerialisation,
    which composes with XLA's autodiff instead of fighting it (a manual
    1F1B interleave would need hand-written per-microbatch vjps).
    """
    s = jax.lax.axis_index("pp")
    m = x_mb.shape[0]
    zero = jnp.zeros_like(x_mb[0])
    ys = jnp.zeros_like(x_mb)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    stage = partial(_stage_apply, config=config, cos=cos, sin=sin)
    if config.remat:
        stage = jax.checkpoint(stage)

    act = zero  # activation leaving this stage last tick
    for t in range(m + n_stages - 1):
        incoming = jax.lax.ppermute(act, "pp", perm)
        feed = x_mb[t] if t < m else zero
        x_in = jnp.where(s == 0, feed, incoming)
        out = stage(stacked_layers, x_in)
        # Last stage completed microbatch t-s this tick (valid when
        # 0 <= t-s < m); store it.
        idx = jnp.clip(t - s, 0, m - 1)
        valid = (s == n_stages - 1) & (t - s >= 0) & (t - s < m)
        current = jax.lax.dynamic_slice_in_dim(ys, idx, 1, axis=0)[0]
        ys = jax.lax.dynamic_update_slice_in_dim(
            ys, jnp.where(valid, out, current)[None], idx, axis=0
        )
        act = out
    return ys


def _pipeline_local(stacked_layers, x_mb, config: LlamaConfig, cos, sin, *, n_stages: int):
    """Schedule + replicate: everyone holds zeros except the last stage,
    one psum broadcasts the pipeline output to all stages (embed/head run
    replicated after). Inference/forward path — training uses
    ``pipeline_llama_loss``, which keeps the activations on the last stage
    and moves only a scalar."""
    s = jax.lax.axis_index("pp")
    ys = _pipeline_schedule(
        stacked_layers, x_mb, config, cos, sin, n_stages=n_stages
    )
    return jax.lax.psum(jnp.where(s == n_stages - 1, ys, jnp.zeros_like(ys)), "pp")


def _prepare_pipeline_inputs(params: Params, tokens: jax.Array, config: LlamaConfig, mesh: Mesh, n_microbatches: int):
    """Shared front half of forward and loss: validation, embedding, rope,
    microbatching, and the shard_map specs. Returns
    (n_stages, m, x_mb, cos, sin, layer_specs, data_spec)."""
    c = config
    n_stages = mesh.shape["pp"]
    if c.n_layers % n_stages:
        raise ValueError(f"{c.n_layers} layers do not divide {n_stages} pp stages")
    m = n_microbatches or n_stages
    b, s_len = tokens.shape
    if b % m:
        raise ValueError(f"batch {b} does not divide {m} microbatches")

    x = _embed_rows(params["embed"], tokens, c.dtype, c.embed_scale)
    cos, sin = _rope(s_len, c.head_dim, c.rope_theta, c.dtype, c.rope_scaling)
    x_mb = x.reshape(m, b // m, s_len, c.d_model)

    layer_specs = jax.tree.map(lambda _: P("pp"), params["layers"])
    # Compose with data parallelism: each dp shard pipelines its slice of
    # every microbatch.
    data_spec = P(None, "dp") if "dp" in mesh.axis_names else P()
    return n_stages, m, x_mb, cos, sin, layer_specs, data_spec


def pipeline_llama_forward(
    params: Params,
    tokens: jax.Array,
    config: LlamaConfig,
    mesh: Mesh,
    n_microbatches: int = 0,
) -> jax.Array:
    """tokens [B, S] → logits [B, S, vocab], transformer blocks pipelined
    over the mesh's ``pp`` axis. `params` must be in stacked layout
    (stack_layer_params). B must divide by n_microbatches (default: pp)."""
    c = config
    b, s_len = tokens.shape
    n_stages, m, x_mb, cos, sin, layer_specs, data_spec = _prepare_pipeline_inputs(
        params, tokens, c, mesh, n_microbatches
    )
    fn = partial(_pipeline_local, config=c, cos=cos, sin=sin, n_stages=n_stages)
    y_mb = jax.shard_map(
        lambda lp, xm: fn(lp, xm),
        mesh=mesh,
        in_specs=(layer_specs, data_spec),
        out_specs=data_spec,
        check_vma=False,
    )(params["layers"], x_mb)

    y = y_mb.reshape(b, s_len, c.d_model)
    y = _rms_norm(y, params["final_norm"], c.norm_eps, c.norm_offset)
    return _mm(y, _unembed_weight(params)).astype(jnp.float32)


def pipeline_llama_loss(
    params: Params,
    tokens: jax.Array,
    config: LlamaConfig,
    mesh: Mesh,
    n_microbatches: int = 0,
) -> jax.Array:
    """Training loss with the head ON the last stage.

    The forward path's psum moves the full [B, S, D] activation to every
    stage — collective volume that defeats the pipeline's memory win at
    scale (round-2 review). Here final-norm, lm_head and the next-token
    NLL run inside the shard_map on the stage that already holds the
    activations; the only cross-stage traffic after the schedule is ONE
    scalar psum."""
    from nos_tpu.models.llama import next_token_nll

    c = config
    b, s_len = tokens.shape
    n_stages, m, x_mb, cos, sin, layer_specs, data_spec = _prepare_pipeline_inputs(
        params, tokens, c, mesh, n_microbatches
    )
    toks_mb = tokens.reshape(m, b // m, s_len)
    has_dp = "dp" in mesh.axis_names and mesh.shape["dp"] > 1

    def local(layers, final_norm, lm_head, xm, tm):
        stage_idx = jax.lax.axis_index("pp")
        ys = _pipeline_schedule(layers, xm, c, cos, sin, n_stages=n_stages)
        y = ys.reshape(-1, s_len, c.d_model)  # microbatch order == batch order
        h = _rms_norm(y, final_norm, c.norm_eps, c.norm_offset)
        logits = _mm(h, lm_head).astype(jnp.float32)
        local_loss = next_token_nll(logits, tm.reshape(-1, s_len))
        # Only the last stage computed real activations: one scalar hop.
        loss = jax.lax.psum(
            jnp.where(stage_idx == n_stages - 1, local_loss, 0.0), "pp"
        )
        if has_dp:
            loss = jax.lax.pmean(loss, "dp")
        return loss

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(layer_specs, P(), P(), data_spec, data_spec),
        out_specs=P(),
        check_vma=False,
    )(params["layers"], params["final_norm"], _unembed_weight(params), x_mb, toks_mb)
