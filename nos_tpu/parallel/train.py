"""Sharded training step.

One jitted function: loss → grad → optimizer update, with NamedSharding
constraints on inputs/outputs so XLA lays out dp gradient all-reduces and
tp collectives over the mesh (no hand-written collectives — the
scaling-book recipe: pick a mesh, annotate shardings, let XLA insert the
psums).

The optimizer is any optax GradientTransformation (AdamW and friends);
its state shards exactly like the parameters — moment subtrees carry the
FSDP/tp NamedShardings leaf for leaf, scalars (step counts) replicate —
so a Llama-3-8B AdamW state is as chip-count-fractional as the params.
The default (no optax passed) remains the momentum-SGD update.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nos_tpu.models.llama import LlamaConfig, llama_loss
from nos_tpu.parallel.sharding import (
    llama_data_sharding,
    llama_param_sharding,
)


def optimizer_state_sharding(opt_state, param_sharding, mesh: Mesh):
    """NamedShardings for an optax state: subtrees structured like the
    params (adam mu/nu, momentum traces, …) get the params' shardings
    wholesale; everything else (step counts, empty states) replicates."""
    params_structure = jax.tree.structure(param_sharding)
    replicated = NamedSharding(mesh, P())

    def is_param_shaped(node) -> bool:
        try:
            return jax.tree.structure(node) == params_structure
        except Exception:  # noqa: BLE001 — non-pytree nodes
            return False

    found = {"n": 0}

    def assign(node):
        if is_param_shaped(node):
            found["n"] += 1
            return param_sharding
        return jax.tree.map(lambda _: replicated, node)

    sharded = jax.tree.map(assign, opt_state, is_leaf=is_param_shaped)
    if found["n"] == 0 and jax.tree.leaves(opt_state):
        # e.g. optax.masked inserting MaskedNode placeholders: the moment
        # tree no longer matches the params' structure and would silently
        # replicate — on a 16 GB chip that is the difference between
        # fitting and OOM, so fail loudly instead.
        raise ValueError(
            "optimizer state contains no params-structured subtree; its "
            "moments would be fully replicated. Restructure the optimizer "
            "(plain adamw/sgd/chain work) or shard its state manually."
        )
    return sharded


def make_train_step(
    mesh: Mesh,
    config: LlamaConfig,
    learning_rate: float = 1e-3,
    momentum: float = 0.9,
    optimizer=None,
    accum_steps: int = 1,
):
    """Returns (train_step, shard_state) where
    train_step(state, tokens) -> (state, loss).

    ``optimizer``: any optax GradientTransformation (state = (params,
    opt_state), sharded via ``optimizer_state_sharding``) — the optimizer
    then OWNS the hyperparameters, so passing non-default learning_rate /
    momentum alongside it is rejected rather than silently ignored. None
    keeps the built-in momentum-SGD update (state = (params, velocity)).

    ``accum_steps`` > 1 enables gradient accumulation: ``tokens``
    [accum·B, S] is processed as ``accum_steps`` sequential micro-batches
    inside one ``lax.scan`` (one backward's activations live at a time —
    effective batch grows without touching peak activation HBM), with
    gradients accumulated in float32 and averaged before ONE optimizer
    update. Equal-sized micro-batches make the result the same gradient
    as a single large batch (pinned by test)."""
    if optimizer is not None and (learning_rate != 1e-3 or momentum != 0.9):
        raise ValueError(
            "learning_rate/momentum configure the built-in SGD update; an "
            "optax optimizer carries its own hyperparameters — set them "
            "there instead"
        )
    param_sharding = llama_param_sharding(mesh, config)
    data_sharding = llama_data_sharding(mesh)
    if optimizer is not None:
        from nos_tpu.models.llama import init_llama_params

        abstract_params = jax.eval_shape(
            lambda: init_llama_params(jax.random.key(0), config)
        )
        opt_sharding = optimizer_state_sharding(
            jax.eval_shape(optimizer.init, abstract_params), param_sharding, mesh
        )
        state_sharding = (param_sharding, opt_sharding)
    else:
        state_sharding = (param_sharding, param_sharding)

    def loss_fn(params, tokens):
        return llama_loss(params, tokens, config, mesh)

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def grad_of(params, tokens):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn)(params, tokens)
        total_b = tokens.shape[0]
        if total_b % accum_steps:
            raise ValueError(
                f"batch {total_b} is not divisible by accum_steps {accum_steps}"
            )
        micro = tokens.reshape(accum_steps, total_b // accum_steps, -1)
        # One hoisted reshard of the whole stack (micro-batch rows spread
        # over dp) instead of a collective inside every scan iteration.
        micro = jax.lax.with_sharding_constraint(
            micro,
            NamedSharding(mesh, P(None, *data_sharding.spec)),
        )

        def acc(carry, batch):
            loss_sum, g_sum = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            g_sum = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), g_sum, grads
            )
            return (loss_sum + loss, g_sum), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        (loss_sum, g_sum), _ = jax.lax.scan(
            acc, (jnp.zeros((), jnp.float32), zeros), micro
        )
        scale = 1.0 / accum_steps
        # Final cast back to the param dtype: the accumulation happened in
        # f32; keeping f32 grads would also flip optax moment dtypes and
        # force a retrace on the second step.
        grads = jax.tree.map(
            lambda g, p: (g * scale).astype(p.dtype), g_sum, params
        )
        return loss_sum * scale, grads

    @partial(
        jax.jit,
        in_shardings=(state_sharding, data_sharding),
        out_shardings=(state_sharding, None),
        donate_argnums=(0,),
    )
    def train_step(state, tokens):
        params, opt = state
        loss, grads = grad_of(params, tokens)
        if optimizer is not None:
            import optax

            updates, opt = optimizer.update(grads, opt, params)
            params = optax.apply_updates(params, updates)
            return (params, opt), loss
        new_velocity = jax.tree.map(
            lambda v, g: momentum * v + g.astype(v.dtype), opt, grads
        )
        new_params = jax.tree.map(
            lambda p, v: p - learning_rate * v, params, new_velocity
        )
        return (new_params, new_velocity), loss

    def shard_state(params, donate: bool = False):
        """Shard (params, optimizer state) onto the mesh — zero velocity
        for the built-in SGD, ``optimizer.init`` (run eagerly on the
        already-sharded params, then placed onto the state shardings) for
        the optax path.

        By default the caller's ``params`` remain valid afterwards: the
        resharding goes through a jitted identity, which always produces
        fresh buffers (``jax.device_put`` aliases when the sharding already
        matches — e.g. on a 1-device mesh — and ``train_step`` then donates
        the caller's own arrays out from under them). Pass ``donate=True``
        to hand the buffers over instead, halving peak HBM when params were
        freshly initialized and will not be reused.
        """
        if donate:
            params = jax.device_put(params, param_sharding)
        else:
            params = jax.jit(lambda p: p, out_shardings=param_sharding)(params)
        if optimizer is not None:
            opt_state = jax.device_put(
                optimizer.init(params), state_sharding[1]
            )
            return (params, opt_state)
        velocity = jax.device_put(
            jax.tree.map(jnp.zeros_like, params), param_sharding
        )
        return (params, velocity)

    return train_step, shard_state
