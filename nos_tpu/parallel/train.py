"""Sharded training step.

One jitted function: loss → grad → SGD-with-momentum update, with
NamedSharding constraints on inputs/outputs so XLA lays out dp gradient
all-reduces and tp collectives over the mesh (no hand-written collectives
— the scaling-book recipe: pick a mesh, annotate shardings, let XLA insert
the psums).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from nos_tpu.models.llama import LlamaConfig, llama_loss
from nos_tpu.parallel.sharding import (
    llama_data_sharding,
    llama_param_sharding,
)


def make_train_step(mesh: Mesh, config: LlamaConfig, learning_rate: float = 1e-3, momentum: float = 0.9):
    """Returns (train_step, shard_state) where
    train_step(state, tokens) -> (state, loss); state = (params, velocity)."""
    param_sharding = llama_param_sharding(mesh, config)
    data_sharding = llama_data_sharding(mesh)
    state_sharding = (param_sharding, param_sharding)

    def loss_fn(params, tokens):
        return llama_loss(params, tokens, config, mesh)

    @partial(
        jax.jit,
        in_shardings=(state_sharding, data_sharding),
        out_shardings=(state_sharding, None),
        donate_argnums=(0,),
    )
    def train_step(state, tokens):
        params, velocity = state
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        new_velocity = jax.tree.map(
            lambda v, g: momentum * v + g.astype(v.dtype), velocity, grads
        )
        new_params = jax.tree.map(
            lambda p, v: p - learning_rate * v, params, new_velocity
        )
        return (new_params, new_velocity), loss

    def shard_state(params, donate: bool = False):
        """Shard (params, zero-velocity) onto the mesh.

        By default the caller's ``params`` remain valid afterwards: the
        resharding goes through a jitted identity, which always produces
        fresh buffers (``jax.device_put`` aliases when the sharding already
        matches — e.g. on a 1-device mesh — and ``train_step`` then donates
        the caller's own arrays out from under them). Pass ``donate=True``
        to hand the buffers over instead, halving peak HBM when params were
        freshly initialized and will not be reused.
        """
        velocity = jax.tree.map(jnp.zeros_like, params)
        if donate:
            params = jax.device_put(params, param_sharding)
        else:
            params = jax.jit(lambda p: p, out_shardings=param_sharding)(params)
        return (params, jax.device_put(velocity, param_sharding))

    return train_step, shard_state
