from nos_tpu.parallel.mesh import mesh_from_devices, mesh_for_slice
from nos_tpu.parallel.sharding import llama_param_sharding, llama_data_sharding
from nos_tpu.parallel.train import make_train_step

__all__ = [
    "llama_data_sharding",
    "llama_param_sharding",
    "make_train_step",
    "mesh_for_slice",
    "mesh_from_devices",
]
