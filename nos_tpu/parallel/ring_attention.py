"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context sequence parallelism for the JAX workloads this suite
schedules (SURVEY.md maps nos's scale axis to TPU slice topology; the
workload-side counterpart is sequence sharding so one carved slice can
train contexts larger than a single chip's HBM).

The sequence axis is block-distributed over the ``sp`` mesh axis. Each
device keeps its query block resident and the K/V blocks rotate around the
ring via ``lax.ppermute`` (neighbor exchanges ride contiguous ICI, never
DCN); softmax is accumulated online (running max / normalizer / weighted
sum, the Milakov-Gimelshein scheme), so the full [S, S] score matrix never
materializes and memory stays O(S·S/n) per chip. Compute is exact — the
result matches dense attention to float tolerance.

Composes with tensor parallelism: heads shard over ``tp``, so the shard_map
block sees [B/dp, S/sp, H/tp, hd] and the ring math is unchanged.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _online_block_update(q, k, v, m, l, acc, q_offset, kv_offset, causal):
    """One ring step: fold the current K/V block into the accumulators.

    q [B,Sq,Kv,g,hd] grouped queries; k/v [B,Skv,Kv,hd]; accumulators in
    float32: m,l [B,Kv,g,Sq], acc [B,Kv,g,Sq,hd].
    """
    hd = q.shape[-1]
    scores = jnp.einsum(
        "bsKgh,btKh->bKgst", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(hd)
    if causal:
        sq, skv = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(sq)
        kv_pos = kv_offset + jnp.arange(skv)
        mask = kv_pos[None, :] <= q_pos[:, None]  # [Sq, Skv]
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)

    block_max = jnp.max(scores, axis=-1)  # [B,Kv,g,Sq]
    new_m = jnp.maximum(m, block_max)
    # Rows fully masked so far have new_m = -inf; exp against 0 keeps the
    # masked probabilities at exp(-inf)=0 instead of exp(nan).
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    probs = jnp.exp(scores - safe_m[..., None])  # [B,Kv,g,Sq,Skv]
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    new_l = l * correction + jnp.sum(probs, axis=-1)
    new_acc = acc * correction[..., None] + jnp.einsum(
        "bKgst,btKh->bKgsh", probs, v.astype(jnp.float32)
    )
    return new_m, new_l, new_acc


def _ring_attention_local(q, k, v, *, axis_name: str, n_shards: int, causal: bool):
    """The per-device block: local q stays, k/v rotate around the ring.

    ``n_shards`` is static (the mesh axis size) so the ring unrolls into a
    scan with a known trip count — reverse-mode AD flows through the
    ppermutes (their transpose is the reverse permute).
    """
    n = n_shards
    my_idx = jax.lax.axis_index(axis_name)
    b, sq, n_q_heads, hd = q.shape
    n_kv_heads = k.shape[2]
    group = n_q_heads // n_kv_heads
    qg = q.reshape(b, sq, n_kv_heads, group, hd)

    m0 = jnp.full((b, n_kv_heads, group, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, n_kv_heads, group, sq), jnp.float32)
    acc0 = jnp.zeros((b, n_kv_heads, group, sq, hd), jnp.float32)
    q_offset = my_idx * sq
    perm = [(j, (j + 1) % n) for j in range(n)]

    def update(k_blk, v_blk, m, l, acc, kv_idx):
        def run():
            return _online_block_update(
                qg, k_blk, v_blk, m, l, acc, q_offset, kv_idx * k_blk.shape[1], causal
            )

        if not causal:
            return run()
        # Fully-future blocks are entirely masked: skip their FLOPs inside
        # the cond (the ring stays synchronous, so this saves compute, not
        # steps).
        return jax.lax.cond(kv_idx > my_idx, lambda: (m, l, acc), run)

    # Own block first, then n-1 permute-and-update rounds: the last
    # exchanged block is consumed, never a wasted hop.
    m, l, acc = update(k, v, m0, l0, acc0, my_idx)

    def step(carry, i):
        k_blk, v_blk, m, l, acc = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        # Block i arrived from i ring hops upstream.
        m, l, acc = update(k_blk, v_blk, m, l, acc, (my_idx - i) % n)
        return (k_blk, v_blk, m, l, acc), None

    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, m, l, acc), jnp.arange(1, n), length=n - 1
    )
    out = acc / l[..., None]  # causal rows always see their own position
    # [B,Kv,g,Sq,hd] -> [B,Sq,Hq*hd]
    out = jnp.moveaxis(out, 3, 1).reshape(b, sq, n_q_heads * hd)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = "tp",
) -> jax.Array:
    """Exact attention with q/k/v [B, S, H, hd] sequence-sharded over
    ``axis_name``. Returns [B, S, Hq·hd]. Axis names absent from the mesh
    are ignored, so the same call works on ('dp','tp'), ('sp',), or
    ('dp','sp','tp') meshes.
    """
    names = mesh.axis_names
    ba = batch_axis if batch_axis in names else None
    sa = axis_name if axis_name in names else None
    ha = head_axis if head_axis in names else None
    if sa is None:
        raise ValueError(f"mesh {names} has no sequence axis {axis_name!r}")
    qkv_spec = P(ba, sa, ha, None)
    out_spec = P(ba, sa, ha)
    fn = partial(
        _ring_attention_local, axis_name=sa, n_shards=mesh.shape[sa], causal=causal
    )
    return jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=out_spec,
        check_vma=False,
    )(q, k, v)
