"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context sequence parallelism for the JAX workloads this suite
schedules (SURVEY.md maps nos's scale axis to TPU slice topology; the
workload-side counterpart is sequence sharding so one carved slice can
train contexts larger than a single chip's HBM).

The sequence axis is block-distributed over the ``sp`` mesh axis. Each
device keeps its query block resident and the K/V blocks rotate around the
ring via ``lax.ppermute`` (neighbor exchanges ride contiguous ICI, never
DCN); softmax is accumulated online (running max / normalizer / weighted
sum, the Milakov-Gimelshein scheme), so the full [S, S] score matrix never
materializes and memory stays O(S·S/n) per chip. Compute is exact — the
result matches dense attention to float tolerance.

Composes with tensor parallelism: heads shard over ``tp``, so the shard_map
block sees [B/dp, S/sp, H/tp, hd] and the ring math is unchanged.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _block_skippable(kv_idx, my_idx, sq, skv, causal, window):
    """Whether a ring block is fully masked for this device's queries —
    the exact inverse of the kernel's block-coverage predicate
    (ops/flash_attention._block_needed), reused so the ring's lax.cond
    skips can never disagree with kernel block coverage."""
    from nos_tpu.ops.flash_attention import _block_needed

    if not causal:
        return jnp.asarray(False)
    return jnp.logical_not(
        _block_needed(sq, skv, my_idx * sq, kv_idx * skv, causal, window)
    )


def _online_block_update(q, k, v, m, l, acc, q_offset, kv_offset, causal, window=None):
    """One ring step: fold the current K/V block into the accumulators.

    q [B,Sq,Kv,g,hd] grouped queries; k/v [B,Skv,Kv,hd]; accumulators in
    float32: m,l [B,Kv,g,Sq], acc [B,Kv,g,Sq,hd].
    """
    hd = q.shape[-1]
    # Inputs stay in the model dtype (bf16) with f32 ACCUMULATION — the
    # MXU's native mode; casting inputs to f32 first would demote the
    # matmul to the slow f32 path (same rule as ops/flash_attention.py).
    scores = jnp.einsum(
        "bsKgh,btKh->bKgst", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(hd)
    if causal:
        sq, skv = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(sq)
        kv_pos = kv_offset + jnp.arange(skv)
        mask = kv_pos[None, :] <= q_pos[:, None]  # [Sq, Skv]
        if window is not None:
            mask = mask & (q_pos[:, None] - kv_pos[None, :] < window)
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)

    block_max = jnp.max(scores, axis=-1)  # [B,Kv,g,Sq]
    new_m = jnp.maximum(m, block_max)
    # Rows fully masked so far have new_m = -inf; exp against 0 keeps the
    # masked probabilities at exp(-inf)=0 instead of exp(nan).
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    probs = jnp.exp(scores - safe_m[..., None])  # [B,Kv,g,Sq,Skv]
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    new_l = l * correction + jnp.sum(probs, axis=-1)
    # Probabilities round to the input dtype for the PV matmul (bf16 MXU,
    # f32 accumulate) — the same rounding the dense training path applies.
    new_acc = acc * correction[..., None] + jnp.einsum(
        "bKgst,btKh->bKgsh", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return new_m, new_l, new_acc


def _ring_attention_local(q, k, v, *, axis_name: str, n_shards: int, causal: bool, window=None):
    """The per-device block: local q stays, k/v rotate around the ring.

    ``n_shards`` is static (the mesh axis size) so the ring unrolls into a
    scan with a known trip count — reverse-mode AD flows through the
    ppermutes (their transpose is the reverse permute).
    """
    n = n_shards
    my_idx = jax.lax.axis_index(axis_name)
    b, sq, n_q_heads, hd = q.shape
    n_kv_heads = k.shape[2]
    group = n_q_heads // n_kv_heads
    qg = q.reshape(b, sq, n_kv_heads, group, hd)

    m0 = jnp.full((b, n_kv_heads, group, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, n_kv_heads, group, sq), jnp.float32)
    acc0 = jnp.zeros((b, n_kv_heads, group, sq, hd), jnp.float32)
    q_offset = my_idx * sq
    perm = [(j, (j + 1) % n) for j in range(n)]

    def update(k_blk, v_blk, m, l, acc, kv_idx):
        def run():
            return _online_block_update(
                qg, k_blk, v_blk, m, l, acc, q_offset, kv_idx * k_blk.shape[1],
                causal, window,
            )

        if not causal:
            return run()
        # Fully-masked blocks (future, or past the sliding band) skip
        # their FLOPs inside the cond (the ring stays synchronous, so
        # this saves compute, not steps).
        skip = _block_skippable(kv_idx, my_idx, sq, k_blk.shape[1], causal, window)
        return jax.lax.cond(skip, lambda: (m, l, acc), run)

    # Own block first, then n-1 permute-and-update rounds: the last
    # exchanged block is consumed, never a wasted hop.
    m, l, acc = update(k, v, m0, l0, acc0, my_idx)

    def step(carry, i):
        k_blk, v_blk, m, l, acc = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        # Block i arrived from i ring hops upstream.
        m, l, acc = update(k_blk, v_blk, m, l, acc, (my_idx - i) % n)
        return (k_blk, v_blk, m, l, acc), None

    (_, _, _, l, acc), _ = jax.lax.scan(
        step, (k, v, m, l, acc), jnp.arange(1, n), length=n - 1
    )
    out = acc / l[..., None]  # causal rows always see their own position
    # [B,Kv,g,Sq,hd] -> [B,Sq,Hq*hd]
    out = jnp.moveaxis(out, 3, 1).reshape(b, sq, n_q_heads * hd)
    return out.astype(q.dtype)


def _ring_shard_map(local_fn, mesh, axis_name, batch_axis, head_axis, out_rank4):
    """Axis resolution + shard_map scaffolding shared by both ring
    implementations. Returns (wrapped_fn, sequence_axis_name)."""
    names = mesh.axis_names
    ba = batch_axis if batch_axis in names else None
    sa = axis_name if axis_name in names else None
    ha = head_axis if head_axis in names else None
    if sa is None:
        raise ValueError(f"mesh {names} has no sequence axis {axis_name!r}")
    qkv_spec = P(ba, sa, ha, None)
    out_spec = P(ba, sa, ha, None) if out_rank4 else P(ba, sa, ha)
    wrapped = jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec),
        out_specs=out_spec,
        check_vma=False,
    )
    return wrapped, sa


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = "tp",
    window: Optional[int] = None,
) -> jax.Array:
    """Exact attention with q/k/v [B, S, H, hd] sequence-sharded over
    ``axis_name``. Returns [B, S, Hq·hd]. Axis names absent from the mesh
    are ignored, so the same call works on ('dp','tp'), ('sp',), or
    ('dp','sp','tp') meshes.
    """
    from nos_tpu.ops.flash_attention import validate_window

    validate_window(causal, window)

    def build(sa):
        return partial(
            _ring_attention_local, axis_name=sa, n_shards=mesh.shape[sa],
            causal=causal, window=window,
        )

    names = mesh.axis_names
    sa0 = axis_name if axis_name in names else None
    if sa0 is None:
        raise ValueError(f"mesh {names} has no sequence axis {axis_name!r}")
    wrapped, _ = _ring_shard_map(
        build(sa0), mesh, axis_name, batch_axis, head_axis, out_rank4=False
    )
    return wrapped(q, k, v)


# ------------------------------------------------------- kernel-backed ring


def _ring_flash_fwd_local(q, k, v, axis_name, n, causal, interpret, window=None):
    """Forward ring with the Pallas flash kernel per K/V block: local q
    stays resident, blocks rotate, (out, lse) partials merge exactly
    (ops/flash_attention.py block APIs)."""
    from nos_tpu.ops.flash_attention import (
        flash_attention_block,
        merge_flash_partials,
    )

    my_idx = jax.lax.axis_index(axis_name)
    sq = q.shape[1]
    q_off = my_idx * sq

    def block(k_blk, v_blk, kv_idx):
        return flash_attention_block(
            q, k_blk, v_blk, q_off, kv_idx * sq, causal=causal,
            interpret=interpret, window=window,
        )

    def folded(out, lse, k_blk, v_blk, kv_idx):
        def run():
            o2, lse2 = block(k_blk, v_blk, kv_idx)
            return merge_flash_partials(out, lse, o2, lse2)

        if not causal:
            return run()
        # Fully-masked blocks (future, or past the band) contribute
        # nothing: skip their kernels.
        skip = _block_skippable(kv_idx, my_idx, sq, sq, causal, window)
        return jax.lax.cond(skip, lambda: (out, lse), run)

    out, lse = block(k, v, my_idx)
    # Carry the partial in f32 across the ring (one rounding at the END,
    # matching the jnp ring's f32 accumulator) — per-hop bf16 rounding
    # would compound with ring size.
    out = out.astype(jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        k_blk, v_blk, out, lse = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        out, lse = folded(out, lse, k_blk, v_blk, (my_idx - i) % n)
        return (k_blk, v_blk, out, lse), None

    (_, _, out, lse), _ = jax.lax.scan(
        step, (k, v, out, lse), jnp.arange(1, n), length=n - 1
    )
    return out.astype(q.dtype), lse


def _ring_flash_bwd_local(q, k, v, out, lse, do, axis_name, n, causal, interpret, window=None):
    """Backward ring: K/V blocks make a FULL revolution carrying their
    gradient accumulators with them, so after n hops each block's dk/dv
    arrives back at its owner fully aggregated; dq accumulates locally.
    The per-block terms need only the local q-row stats (out, lse, do) —
    the standard flash backward identity."""
    from nos_tpu.ops.flash_attention import _delta, flash_block_grads

    my_idx = jax.lax.axis_index(axis_name)
    sq = q.shape[1]
    q_off = my_idx * sq
    perm = [(j, (j + 1) % n) for j in range(n)]
    dq0 = jnp.zeros(q.shape, jnp.float32)
    # Loop-invariant row stats: computed ONCE, not per ring hop.
    delta = _delta(do, out)

    def contribution(k_blk, v_blk, kv_idx):
        # f32 block grads: the cross-ring sums below accumulate in f32 and
        # round once at the end (the single-chip backward's contract).
        return flash_block_grads(
            q, k_blk, v_blk, out, lse, do, q_off, kv_idx * sq,
            causal=causal, interpret=interpret,
            grad_dtype=jnp.float32, delta=delta, window=window,
        )

    def step(carry, i):
        k_blk, v_blk, dk_acc, dv_acc, dq = carry
        kv_idx = (my_idx - i) % n

        def run():
            dq_c, dk_c, dv_c = contribution(k_blk, v_blk, kv_idx)
            return (
                dk_acc + dk_c,
                dv_acc + dv_c,
                dq + dq_c,
            )

        if causal:
            skip = _block_skippable(kv_idx, my_idx, sq, sq, causal, window)
            dk_acc, dv_acc, dq = jax.lax.cond(
                skip, lambda: (dk_acc, dv_acc, dq), run
            )
        else:
            dk_acc, dv_acc, dq = run()
        # Rotate the block WITH its accumulator: after n hops both are home.
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        return (k_blk, v_blk, dk_acc, dv_acc, dq), None

    carry = (k, v, jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32), dq0)
    (k_end, v_end, dk, dv, dq), _ = jax.lax.scan(
        step, carry, jnp.arange(n), length=n
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def make_ring_flash_local(axis_name: str, n: int, causal: bool, interpret: bool, window=None):
    """The shard_map-body ring-flash attention with a hand-written ring
    backward (Pallas kernels are forward primitives; autodiff cannot see
    through them, so the vjp replays the ring explicitly)."""

    @jax.custom_vjp
    def ring_flash(q, k, v):
        out, _ = _ring_flash_fwd_local(
            q, k, v, axis_name, n, causal, interpret, window
        )
        return out

    def fwd(q, k, v):
        out, lse = _ring_flash_fwd_local(
            q, k, v, axis_name, n, causal, interpret, window
        )
        return out, (q, k, v, out, lse)

    def bwd(res, do):
        q, k, v, out, lse = res
        return _ring_flash_bwd_local(
            q, k, v, out, lse, do, axis_name, n, causal, interpret, window
        )

    ring_flash.defvjp(fwd, bwd)
    return ring_flash


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axis: Optional[str] = "dp",
    head_axis: Optional[str] = "tp",
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """``ring_attention`` with the Pallas flash kernel doing each block's
    math: same exactness contract, kernel-rate compute, O(blk) VMEM. The
    jnp path remains as the portable fallback (and the oracle in tests)."""
    from nos_tpu.ops.flash_attention import validate_window

    validate_window(causal, window)
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"q heads {q.shape[2]} not a multiple of kv heads {k.shape[2]}"
        )
    names = mesh.axis_names
    sa0 = axis_name if axis_name in names else None
    if sa0 is None:
        raise ValueError(f"mesh {names} has no sequence axis {axis_name!r}")
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    fn = make_ring_flash_local(sa0, mesh.shape[sa0], causal, interpret, window)
    wrapped, _ = _ring_shard_map(
        fn, mesh, axis_name, batch_axis, head_axis, out_rank4=True
    )
    out = wrapped(q, k, v)
    b, s, hq, hd = q.shape
    return out.reshape(b, s, hq * hd)
