"""Cursor pagination + JSONL helpers for the /debug endpoints.

All three O(cluster) debug surfaces (capacity nodes, trace summaries,
timeline series) paginate the same way: items are ordered by a stable
string key, the cursor is the last key of the previous page, and a page
is the first ``limit`` items strictly after it. Keys are compared as
plain strings, so zero-padded names (node-00042) page in cluster order.
An empty ``next_cursor`` means the listing is exhausted.
"""
from __future__ import annotations

import bisect
import json
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple


def paginate(
    keys: Sequence[str], limit: int = 0, cursor: str = ""
) -> Tuple[List[str], str]:
    """Page through ``keys`` (must be sorted ascending). Returns
    ``(page, next_cursor)``; ``limit`` <= 0 means the whole remainder."""
    start = bisect.bisect_right(keys, cursor) if cursor else 0
    if limit and limit > 0:
        page = list(keys[start : start + limit])
        more = start + limit < len(keys)
        return page, (page[-1] if page and more else "")
    return list(keys[start:]), ""


def page_params(query: Dict[str, str], default_limit: int = 0) -> dict:
    """Decode ?pool=/?limit=/?cursor=/?format= into validated kwargs.
    A malformed limit raises ValueError (the HTTP layer maps it to 400)."""
    limit = default_limit
    if "limit" in query:
        limit = int(query["limit"])
        if limit < 0:
            raise ValueError("limit must be >= 0")
    return {
        "pool": query.get("pool", ""),
        "limit": limit,
        "cursor": query.get("cursor", ""),
        "jsonl": query.get("format", "") == "jsonl",
    }


def jsonl_lines(records: Iterable[dict]) -> Iterator[bytes]:
    """Encode records one line at a time — the chunked-response writer
    consumes this without ever holding the whole document."""
    for record in records:
        yield (json.dumps(record, sort_keys=True) + "\n").encode()


def page_envelope(
    payload: dict, next_cursor: str, limit: int, total: Optional[int] = None
) -> dict:
    """Uniform pagination trailer appended to paged JSON documents."""
    page = {"limit": limit, "next_cursor": next_cursor}
    if total is not None:
        page["total"] = total
    payload["page"] = page
    return payload
