"""Wire an ObservabilityConfig onto the process-wide telemetry singletons.

Kept out of ``obsplane/__init__`` (and imported function-locally by
``cmd/run.py`` and the chaos driver) because it touches the
``util.metrics``/``util.tracing`` globals — everything else in this
package stays importable without them.
"""
from __future__ import annotations

from typing import Callable

from nos_tpu.obsplane import governor


def apply_observability(obs, registry=None, tracer=None) -> Callable[[], None]:
    """Apply series budgets + trace retention; returns a revert callable.

    The registry and tracer are process-global and shared across every
    test in one pytest run, so callers that apply non-default policy
    (the chaos soak, the bench's A/B arms) MUST call the returned revert
    in a ``finally``.
    """
    from nos_tpu.util import metrics as metrics_mod
    from nos_tpu.util import tracing as tracing_mod

    registry = registry if registry is not None else metrics_mod.REGISTRY
    tracer = tracer if tracer is not None else tracing_mod.TRACER

    budgets, default = governor.budgets_from(obs)
    prev_budgets = registry.apply_series_budgets(budgets, default=default)
    prev_policy = tracer.store.set_retention(
        tracing_mod.RetentionPolicy(
            tail_capacity=obs.trace_tail_capacity,
            boring_sample_n=obs.trace_boring_sample_n,
            slow_thresholds=dict(obs.trace_slow_thresholds),
        )
    )

    def revert() -> None:
        registry.restore_series_budgets(prev_budgets)
        tracer.store.set_retention(prev_policy)

    return revert
