"""Cardinality-governor config resolution and reporting.

The governor itself is three lines of admission logic inside
``util.metrics._admit_child`` (budget check → deterministic ``_other``
fold); this module owns the parts that don't belong on the metric hot
path: translating ``ObservabilityConfig.series_budget`` into registry
budgets and summarizing the resulting series accounting for the bench,
chaos oracles, and /debug surfaces.

Admission is a deterministic function of the admitted-series set: the
first ``budget`` distinct label sets a family ever sees are exact,
everything after folds into the single ``_other`` child. Replaying the
same event stream therefore reproduces the same exposition bytes — the
property ``tests/obsplane`` pins and the tampered-policy test proves
fragile under a different budget.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple


def budgets_from(obs) -> Tuple[Dict[str, int], Optional[int]]:
    """(per-family budgets, default budget) from an ObservabilityConfig.
    A 0/None default means unbudgeted, matching the registry contract."""
    budgets = {name: int(v) for name, v in (obs.series_budget or {}).items()}
    default = obs.series_budget_default
    if default is not None and default <= 0:
        default = None
    return budgets, default


def governor_report(registry) -> dict:
    """Totals + per-family series accounting, sorted and JSON-ready.

    ``families`` only lists families that hold series or carry a budget;
    ``over_budget`` names the ones actively folding into ``_other`` —
    the list the chaos ``governor-clean`` oracle checks against the
    budgets it set on purpose.
    """
    families = registry.series_report()
    active = sum(f["exact"] + f["overflow"] for f in families.values())
    dropped = sum(f["dropped"] for f in families.values())
    return {
        "active_series": active,
        "dropped_series": dropped,
        "over_budget": sorted(
            name for name, f in families.items() if f["dropped"]
        ),
        "families": families,
    }
