"""Fleet-scale observability plane (PR 19).

The telemetry stack (metrics registry, trace store, timeline sampler,
debug endpoints) was built and benched at 1k–16k nodes; this package
holds the pieces that make it survive 100k nodes / 1M pods:

- ``governor``  — config→series-budget resolution and the cardinality
  report read by the bench and /debug surfaces (the enforcement itself
  lives inside ``util.metrics`` so the hot path pays no import).
- ``streaming`` — cursor pagination and JSONL-line helpers shared by
  ``/debug/capacity``, ``/debug/traces``, and ``/debug/timeline`` so no
  endpoint materializes an O(cluster) document.
- ``apply``     — wires an ``ObservabilityConfig`` onto the process-wide
  registry and tracer, returning a revert callable (the chaos harness
  applies budgets around a run and must leave the shared registry
  untouched). Imported function-locally to keep this package cycle-free.

Only the pure modules are imported here; ``apply`` pulls in the metric
and tracing singletons and stays behind a local import at call sites.
"""
from nos_tpu.obsplane import governor, streaming  # noqa: F401

__all__ = ["governor", "streaming"]
