from nos_tpu.data.pipeline import BatchLoader, pack_documents, prefetch_to_device

__all__ = ["BatchLoader", "pack_documents", "prefetch_to_device"]
