"""Input pipeline: host-side batching with device prefetch.

The IO half of the training runtime (the reference has no data loader —
its workloads are Pods; a training framework needs one). TPU-first
shape:

- batches are assembled on HOST (numpy) — tokenization/packing never
  touches the accelerator;
- ``prefetch_to_device`` keeps ``depth`` batches in flight: the next
  batch's host→device DMA overlaps the current step's compute, so the
  MXU never waits on PCIe/DCN feeds;
- every batch lands ALREADY SHARDED (``jax.device_put`` with the mesh's
  data NamedSharding) — dp shards get their slice directly, no
  scatter-from-one-device hop;
- under multi-host (``jax.process_count() > 1``) each process feeds only
  its addressable shard of the batch: the loader strides the sample
  stream by process index, the standard per-host data-parallel feed.

Deterministic: one integer seed fixes the sample order for every epoch
across restarts — resuming from an orbax checkpoint at step N replays
the exact stream by fast-forwarding the generator.
"""
from __future__ import annotations

import collections
import queue
import threading
from typing import Iterable, Iterator, List, Optional

import numpy as np


def pack_documents(
    documents: Iterable[np.ndarray],
    seq_len: int,
    eos_id: int,
) -> Iterator[np.ndarray]:
    """Greedy sequence packing: concatenate token documents separated by
    ``eos_id`` and emit dense [seq_len] windows — no padding FLOPs, the
    standard pretraining feed."""
    buffer: List[int] = []
    for doc in documents:
        buffer.extend(int(t) for t in doc)
        buffer.append(eos_id)
        while len(buffer) >= seq_len:
            yield np.asarray(buffer[:seq_len], np.int32)
            del buffer[:seq_len]


class BatchLoader:
    """Deterministic host-side batch stream over a token corpus.

    ``corpus``: one long int32 token array (memory-mapped files work —
    anything ndarray-like with __getitem__ slicing). Samples are random
    seq_len windows drawn by a seeded generator; ``skip(n)`` fast-forwards
    past n batches for checkpoint-resume replay.
    """

    def __init__(
        self,
        corpus,
        batch: int,
        seq_len: int,
        seed: int = 0,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
    ) -> None:
        if len(corpus) < seq_len + 1:
            raise ValueError(
                f"corpus of {len(corpus)} tokens is shorter than seq_len {seq_len}"
            )
        self.corpus = corpus
        self.batch = batch
        self.seq_len = seq_len
        self.seed = seed
        if process_index is None or process_count is None:
            try:
                import jax

                process_index = jax.process_index()
                process_count = jax.process_count()
            except Exception:  # noqa: BLE001 — host-only usage
                process_index, process_count = 0, 1
        if batch % process_count:
            raise ValueError(
                f"global batch {batch} does not divide {process_count} processes"
            )
        self.process_index = process_index
        self.process_count = process_count
        self.local_batch = batch // process_count
        self._rng = np.random.default_rng(seed)

    def skip(self, n_batches: int) -> None:
        """Fast-forward (checkpoint resume): replays the RNG stream — only
        the start-index draws, never the corpus copies — so batch N after
        a restart equals batch N of the original run at negligible cost."""
        for _ in range(n_batches):
            self._draw_starts()

    def _draw_starts(self) -> np.ndarray:
        # One GLOBAL draw per batch; every process takes its own stride of
        # the same sample list, so the union across processes is exactly
        # the single-process batch (bitwise-stable resharding).
        return self._rng.integers(0, len(self.corpus) - self.seq_len, size=self.batch)

    def _draw(self) -> np.ndarray:
        starts = self._draw_starts()
        mine = starts[self.process_index::self.process_count]
        return np.stack(
            [np.asarray(self.corpus[s:s + self.seq_len], np.int32) for s in mine]
        )

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self._draw()


def prefetch_to_device(
    host_batches: Iterable[np.ndarray],
    sharding,
    depth: int = 2,
) -> Iterator:
    """Wrap a host batch iterator so device transfer runs ``depth`` batches
    ahead on a background thread: the jax.device_put (async dispatch +
    DMA) of batch N+1 overlaps step N's compute. ``sharding`` is the data
    NamedSharding (nos_tpu.parallel.sharding.llama_data_sharding), so each
    batch arrives sharded over dp/sp with no further movement."""
    import jax

    done = object()
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    error: collections.deque = collections.deque(maxlen=1)
    stop = threading.Event()

    def put(item) -> bool:
        # Bounded, abandonment-aware put: an early-stopping consumer sets
        # `stop`, and the feeder must exit rather than block forever on a
        # full queue holding pinned device buffers.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def feeder() -> None:
        try:
            for host_batch in host_batches:
                if not put(jax.device_put(host_batch, sharding)):
                    return
        except Exception as e:  # noqa: BLE001 — surfaced on the consumer side
            error.append(e)
        finally:
            put(done)

    thread = threading.Thread(target=feeder, name="data-prefetch", daemon=True)
    thread.start()

    try:
        while True:
            item = q.get()
            if item is done:
                if error:
                    raise error.popleft()
                return
            yield item
    finally:
        # GeneratorExit (consumer stopped early) or normal exhaustion:
        # release the feeder and drop any buffered batches.
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
