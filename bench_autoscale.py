"""Autoscaler benchmark: the full control loop on the virtual cost clock.

Closes the loop the serving bench (bench_serve.py) left open: a seeded
diurnal workload drives per-replica cost-model engines (slo/routing.py),
their retired requests feed per-model SLO engines, the burn rates land in
the autoscaler's signal registry, and the ModelServing reconciler turns
verdicts into replica Pods that the REAL suite places — scheduler gang
handshake, partitioner carve, sim-kubelet admission — on a live
SimCluster. Nothing shortcuts the API server: the bench only writes
ModelServing objects and arrival streams.

Two models tell the whole story:

  chat   hot, min 1 / max 3: rides the diurnal wave — burn-rate scale-up
         into the peak, budget-surplus scale-down off it.
  batch  cold, min 0 / max 1: its arrivals stop mid-run, so it idles out,
         scales to zero (chips held briefly in cold-start grace, then
         reclaimed to no-demand), having cold-started at t=0 with an
         honest backlog TTFT penalty.

Determinism: every number in the report derives from the seed and the
virtual clocks. The autoscaler is stepped SYNCHRONOUSLY once per control
epoch (the cluster is built without the async autoscaler component), the
cluster is driven to convergence between epochs, and the shadow capacity
ledger integrates only across settled epoch boundaries — so the committed
BENCH_autoscale.json is byte-identical across runs and machines.

  make bench-autoscale
  python bench_autoscale.py --smoke        # the autoscale-smoke tier
  python bench_autoscale.py --output BENCH_autoscale.json
"""
from __future__ import annotations

import argparse
import json
import time as _time

from nos_tpu.api.config import AutoscalerConfig, GpuPartitionerConfig, SchedulerConfig
from nos_tpu.api.v1alpha1 import labels
from nos_tpu.api.v1alpha1.modelserving import ModelServing, ModelServingSpec
from nos_tpu.capacity.ledger import CapacityLedger
from nos_tpu.chaos.oracles import actuation_converged
from nos_tpu.cmd.cluster import build_cluster
from nos_tpu.cmd.run import seed_node
from nos_tpu.controllers.autoscaler import ModelServingReconciler, SignalRegistry, policy
from nos_tpu.controllers.autoscaler.controller import serving_key
from nos_tpu.kube.controller import Request
from nos_tpu.kube.events import EventRecorder
from nos_tpu.kube.objects import ObjectMeta
from nos_tpu.scheduler.plugins.reservation import RESERVED_FOR
from nos_tpu.slo.driver import ModelProfile, WorkloadConfig, build_arrivals, percentiles
from nos_tpu.slo.engine import SLOEngine
from nos_tpu.slo.routing import ReplicaRouter

# One control decision per EPOCH_S virtual seconds — the bench analogue
# of the controller's resync_seconds.
EPOCH_S = 5.0
# Virtual cost of waking a scaled-to-zero model (weight load + warmup):
# a cold-started replica is ready this long after its control epoch.
COLD_START_MODEL_COST_S = 2.0
# The cold model's arrivals stop at this fraction of the run, so its
# idle-out + scale-to-zero + grace expiry all fit inside the trace.
COLD_MODEL_CUTOFF_FRAC = 0.45

CHAT_SLOS = ("p95 ttft < 400ms", "p99 e2e < 5s")
BATCH_SLOS = ("p95 ttft < 10s",)


def _servings() -> list:
    return [
        ModelServing(
            metadata=ObjectMeta(name="chat", namespace="default"),
            spec=ModelServingSpec(
                model="chat",
                slice_profile="2x4",
                min_replicas=1,
                max_replicas=3,
                slos=list(CHAT_SLOS),
                cold_start_grace_seconds=30.0,
                target_queue_depth=8,
                scale_down_budget_surplus=0.5,
            ),
        ),
        ModelServing(
            metadata=ObjectMeta(name="batch", namespace="default"),
            spec=ModelServingSpec(
                model="batch",
                slice_profile="2x4",
                min_replicas=0,
                max_replicas=1,
                slos=list(BATCH_SLOS),
                scale_to_zero_idle_seconds=30.0,
                cold_start_grace_seconds=40.0,
                target_queue_depth=4,
            ),
        ),
    ]


def _bound(store, ms) -> list:
    key = serving_key(ms)
    return sorted(
        p.metadata.name
        for p in store.list("Pod", namespace=ms.metadata.namespace)
        if p.metadata.labels.get(labels.MODEL_SERVING_LABEL) == key
        and p.metadata.deletion_timestamp is None
        and p.spec.node_name
    )


def _settle_violations(store) -> list:
    out = []
    for p in store.list("Pod"):
        if p.metadata.deletion_timestamp is None and not p.spec.node_name:
            out.append(f"pod {p.metadata.namespace}/{p.metadata.name} unbound")
    out += actuation_converged(store)
    for n in store.list("Node"):
        if RESERVED_FOR in n.metadata.annotations:
            out.append(f"node {n.metadata.name} holds a board reservation")
    return out


def _converge(cluster, deadline_s: float = 30.0) -> None:
    """Drive the cluster to a settled state in WALL time so the next
    virtual-time observation integrates over a deterministic snapshot."""
    deadline = _time.monotonic() + deadline_s
    while True:
        cluster.wait_idle(timeout=1.0)
        violations = _settle_violations(cluster.store)
        if not violations:
            return
        if _time.monotonic() >= deadline:
            raise RuntimeError(
                "cluster failed to settle: " + "; ".join(violations[:8])
            )
        _time.sleep(0.02)


def run_bench(seed: int = 0, duration_s: float = 240.0, rate_rps: float = 14.0) -> dict:
    workload = WorkloadConfig(
        seed=seed,
        duration_s=duration_s,
        rate_rps=rate_rps,
        diurnal_amplitude=0.6,
        diurnal_period_s=duration_s,
        models=(
            ModelProfile(name="chat", weight=0.85),
            ModelProfile(name="batch", weight=0.15),
        ),
    )
    cutoff = COLD_MODEL_CUTOFF_FRAC * duration_s
    # Post-filtering the cold model keeps the thinning draws (and hence
    # every other arrival) aligned with the unfiltered seed.
    arrivals = [
        a
        for a in build_arrivals(workload)
        if a.model != "batch" or a.t <= cutoff
    ]
    by_model = {"chat": [], "batch": []}
    for a in arrivals:
        by_model[a.model].append(a)

    state = {"now": 0.0}
    signals = SignalRegistry(now_fn=lambda: state["now"])
    cluster = build_cluster(
        partitioner_config=GpuPartitionerConfig(
            batch_window_timeout_seconds=1.0, batch_window_idle_seconds=0.05
        ),
        scheduler_config=SchedulerConfig(retry_seconds=0.1),
    )
    shadow = CapacityLedger(cluster.store, metrics=False)
    for i in range(4):
        cluster.add_tpu_node(seed_node({"name": f"tpu-{i}", "chips": 8}))
    servings = _servings()
    for ms in servings:
        ms.spec.validate()
        cluster.store.create(ms)

    # Slow window at half the run: the ramp's burn ages out in time for
    # the budget-surplus scale-down gate to reopen off-peak.
    slo_engines = {
        ms.spec.model: SLOEngine(
            list(ms.spec.slos), fast_window_s=15.0, slow_window_s=duration_s / 2.0
        )
        for ms in servings
    }
    records = {m: [] for m in slo_engines}

    def _sink(model):
        def sink(rec):
            records[model].append(rec)
            slo_engines[model].record(rec)

        return sink

    router = ReplicaRouter(
        signals=signals,
        max_slots=4,
        ttft_targets={
            m: e.latency_targets().get("ttft") for m, e in slo_engines.items()
        },
        e2e_targets={
            m: e.latency_targets().get("e2e") for m, e in slo_engines.items()
        },
        on_complete={m: _sink(m) for m in slo_engines},
    )
    reconciler = ModelServingReconciler(
        cluster.store,
        AutoscalerConfig(
            # Half a diurnal period: scale-down probes at most twice per
            # cycle, so a burn-free lull NEAR the peak cannot shed the
            # replica the descending half of the wave still needs.
            scale_down_stable_seconds=duration_s / 2.0,
            recent_activity_seconds=20.0,
        ),
        signals=signals,
        recorder=EventRecorder(
            cluster.store, component="nos-autoscaler", clock=signals.now
        ),
    )

    cluster.start()
    try:
        # Warm boot: min_replicas placed before the first arrival.
        for ms in servings:
            reconciler.reconcile(
                Request(name=ms.metadata.name, namespace=ms.metadata.namespace)
            )
        _converge(cluster)
        shadow.observe(0.0)
        for ms in servings:
            router.sync_replicas(
                ms.spec.model, _bound(cluster.store, ms), ready_t=0.0
            )

        timeline = []
        scale_events = {}
        cold_penalties = []
        # Post-warm-boot statuses: the boot to min_replicas is not a scale
        # event, so the first counted transition diffs against it.
        prev_desired = {
            ms.metadata.name: cluster.store.get(
                "ModelServing", ms.metadata.name, ms.metadata.namespace
            ).status.desired_replicas
            for ms in servings
        }
        max_ready = {m: 0 for m in slo_engines}
        cursor = {m: 0 for m in by_model}
        peak_row = None
        peak_t = duration_s / 4.0
        final_eval = {}

        epochs = int(round(duration_s / EPOCH_S))
        for k in range(1, epochs + 1):
            t1 = k * EPOCH_S
            for model in sorted(by_model):
                stream = by_model[model]
                i = cursor[model]
                j = i
                while j < len(stream) and stream[j].t <= t1:
                    j += 1
                router.drive(model, stream[i:j], epoch_end=t1)
                cursor[model] = j
            for model in sorted(slo_engines):
                ev = slo_engines[model].evaluate(now=t1)
                slos = ev["slos"]
                signals.update(
                    model,
                    burn_fast=max((s["fast"]["burn_rate"] for s in slos), default=0.0),
                    burn_slow=max((s["slow"]["burn_rate"] for s in slos), default=0.0),
                    error_budget_remaining=min(
                        (s["error_budget_remaining"] for s in slos), default=1.0
                    ),
                )
                final_eval[model] = slos
            state["now"] = t1
            for ms in servings:
                reconciler.reconcile(
                    Request(name=ms.metadata.name, namespace=ms.metadata.namespace)
                )
            _converge(cluster)

            row = {"t": round(t1, 3)}
            for ms in servings:
                fresh = cluster.store.get(
                    "ModelServing", ms.metadata.name, ms.metadata.namespace
                )
                model = fresh.spec.model
                bound = _bound(cluster.store, fresh)
                was_zero = not router.engines(model)
                cold = (
                    was_zero
                    and bound
                    and fresh.status.last_verdict == policy.VERDICT_COLD_START
                )
                ready_t = t1 + (COLD_START_MODEL_COST_S if cold else 0.0)
                if cold:
                    cold_penalties.extend(
                        round(ready_t - a.t, 6)
                        for a in router.backlog.get(model, [])
                    )
                router.sync_replicas(model, bound, ready_t=ready_t)
                if fresh.status.desired_replicas != prev_desired[ms.metadata.name]:
                    verdict = fresh.status.last_verdict
                    scale_events[verdict] = scale_events.get(verdict, 0) + 1
                    prev_desired[ms.metadata.name] = fresh.status.desired_replicas
                max_ready[model] = max(max_ready[model], len(bound))
                sig = signals.get(model)
                row[model] = {
                    "desired": fresh.status.desired_replicas,
                    "ready": len(bound),
                    "verdict": fresh.status.last_verdict,
                    "burn_fast": round(sig.burn_fast, 4),
                }
            timeline.append(row)
            shadow.observe(t1)
            if peak_row is None and t1 >= peak_t:
                # "Compliant at peak" is a fast-window question: at the
                # height of the wave, is the SLO being met right now? The
                # slow window renders the run-level verdict under
                # models.*.slo (it still contains mostly ramp at t=peak).
                peak_row = {
                    "t": round(t1, 3),
                    "by_model": {
                        m: {
                            "compliant": all(
                                s["fast"]["burn_rate"] <= 1.0 for s in final_eval[m]
                            ),
                            "burn_fast": round(
                                max(s["fast"]["burn_rate"] for s in final_eval[m]), 4
                            ),
                        }
                        for m in sorted(final_eval)
                    },
                }

        cold_starts = sum(
            cluster.store.get(
                "ModelServing", ms.metadata.name, ms.metadata.namespace
            ).status.cold_starts
            for ms in servings
        )
        return {
            "workload": {
                "seed": seed,
                "duration_s": duration_s,
                "rate_rps": rate_rps,
                "diurnal_amplitude": workload.diurnal_amplitude,
                "epoch_s": EPOCH_S,
                "cold_model_cutoff_s": round(cutoff, 3),
                "arrivals": {m: len(v) for m, v in by_model.items()},
            },
            "servings": {
                ms.metadata.name: {
                    "model": ms.spec.model,
                    "slice_profile": ms.spec.slice_profile,
                    "chips_per_replica": ms.spec.chips_per_replica,
                    "min_replicas": ms.spec.min_replicas,
                    "max_replicas": ms.spec.max_replicas,
                    "slos": list(ms.spec.slos),
                }
                for ms in servings
            },
            "models": {
                m: {
                    "requests_completed": len(records[m]),
                    "ttft_s": percentiles(
                        [r.ttft_s for r in records[m] if r.ttft_s is not None]
                    ),
                    "e2e_s": percentiles(
                        [r.e2e_s for r in records[m] if r.e2e_s is not None]
                    ),
                    "queue_wait_s": percentiles(
                        [
                            r.queue_wait_s
                            for r in records[m]
                            if r.queue_wait_s is not None
                        ]
                    ),
                    "slo": [
                        {
                            "spec": s["spec"],
                            "compliant": s["compliant"],
                            "burn_fast": round(s["fast"]["burn_rate"], 4),
                            "burn_slow": round(s["slow"]["burn_rate"], 4),
                            "error_budget_remaining": s["error_budget_remaining"],
                        }
                        for s in final_eval[m]
                    ],
                }
                for m in sorted(records)
            },
            "timeline": timeline,
            "scale_events": scale_events,
            "cold_start": {
                "count": cold_starts,
                "ttft_penalty_s": percentiles(cold_penalties),
            },
            "peak": {
                "slos_compliant": all(
                    v["compliant"] for v in peak_row["by_model"].values()
                ),
                **peak_row,
            },
            "replicas": {
                "max_ready": max_ready,
                "final": {
                    ms.spec.model: len(_bound(cluster.store, ms)) for ms in servings
                },
            },
            "capacity": {
                "total_chip_seconds": round(shadow.total_chip_seconds, 3),
                "busy_chip_seconds": round(shadow.busy_chip_seconds, 3),
                "idle_chip_seconds": {
                    b: round(v, 3) for b, v in shadow.idle_chip_seconds.items()
                },
            },
        }
    finally:
        cluster.stop()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--duration", type=float, default=240.0,
        help="virtual seconds of arrivals (one diurnal period)",
    )
    parser.add_argument(
        "--rate", type=float, default=14.0,
        help="mean arrival rate (requests / virtual second)",
    )
    parser.add_argument("--output", default=None, help="write JSON here")
    parser.add_argument(
        "--smoke", action="store_true",
        help="half-length run for the autoscale-smoke tier",
    )
    args = parser.parse_args()
    if args.smoke:
        args.duration = min(args.duration, 120.0)
    report = run_bench(
        seed=args.seed, duration_s=args.duration, rate_rps=args.rate
    )
    body = json.dumps(report, indent=2, sort_keys=True)
    if args.output:
        with open(args.output, "w") as f:
            f.write(body + "\n")
    print(body)


if __name__ == "__main__":
    main()
