"""Longitudinal soak benchmark: the health timeline watching a real
steady-state control plane for hundreds of plan cycles.

The soak drives the pool-sharded planning pipeline (per-pool persistent
planners + cross-pool merge — the same code path the partitioner
controller runs) at 1024 nodes / 8 pools on a pure virtual clock, with
the placement forecaster and the model-autoscaler decision function
riding the same timeline, while a TimelineStore samples every metric
family, the SizeRegistry, the WedgeWatchdog, and process vitals each
virtual interval. The acceptance bar:

- every timed cycle takes the incremental path and the merge invariants
  hold (a regression here is a planner bug, not a bench artifact);
- ZERO leak/stall findings after the workload drains — the memos, rings
  and caches the SizeRegistry watches must plateau, and the registered
  periodic loop must keep beating;
- sampling overhead stays within 2% of the steady-state replan p50
  (total sampling time amortized over all plan cycles), guarded by
  interleaving: odd cycles sample, even cycles do not, and the sampled
  cycles' replan p50 may not degrade past the budget;
- the run's flight log replays with zero drift (timeline findings, if
  any ever fire, recompute bit-exactly from their recorded windows).

Determinism: every report field derives from the seed and the virtual
clock — wall-clock measurements reduce to booleans before they reach the
report, so the committed BENCH_soak.json is byte-identical across runs.

  make bench-soak
  python bench_soak.py --output BENCH_soak.json
"""
from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import sys
import time

from bench_planner import (
    _ages,
    _framework,
    build_steady_node,
    make_steady_cluster,
    make_steady_pending,
    node_name,
    pool_of,
    steady_annotations,
)
from nos_tpu.api.config import AutoscalerConfig
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.api.v1alpha1.modelserving import ModelServingSpec
from nos_tpu.capacity.ledger import CapacityLedger
from nos_tpu.cmd.partitioner import build_sim_framework, register_indexers
from nos_tpu.controllers.autoscaler import policy
from nos_tpu.controllers.autoscaler.signals import SignalRegistry
from nos_tpu.forecast import PlacementForecaster
from nos_tpu.kube.objects import Container, ObjectMeta, Pod, PodSpec
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import ClusterState, Planner
from nos_tpu.partitioning.core.pools import (
    check_merge_invariants,
    merge_pool_states,
    node_capacities,
    partition_pools,
    run_pool_plans,
    split_pending,
    split_snapshot,
)
from nos_tpu.partitioning.tpu import TpuSnapshotTaker
from nos_tpu.record import FlightRecorder
from nos_tpu.record.replay import ReplaySession
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL
from nos_tpu.timeline import SIZES, WATCHDOG, DetectorPolicy, TimelineStore, detectors

SEED = 17
NODES = 1024
POOLS = 8
PENDING_PODS = 320
CYCLES = 220
CYCLE_S = 0.5       # virtual seconds per plan cycle
CHURN = 0.02
OVERHEAD_BUDGET = 0.02
FORECAST_EVERY = 8  # forecast cadence in cycles (snapshot cost at 1024 nodes)
STORE_NODES = 64    # store-side cluster the forecaster/ledger observe
MODEL = "soak-model"


def gang_pod(name: str, gang: str, size: int) -> Pod:
    pod = Pod(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PodSpec(
            containers=[
                Container(requests={constants.tpu_slice_resource("2x2"): 1})
            ],
            scheduler_name=constants.SCHEDULER_NAME,
        ),
    )
    pod.metadata.labels[GANG_NAME_LABEL] = gang
    pod.metadata.labels[GANG_SIZE_LABEL] = str(size)
    return pod


def build_gang_stream(rng: random.Random, cycles: int):
    """Seeded gang arrivals across the soak: (arrival cycle, size,
    cycles-until-bind, cycles-until-complete)."""
    jobs = []
    cycle = 0
    i = 0
    while cycle < cycles - 20:
        cycle += rng.randint(2, 6)
        jobs.append(
            {
                "name": f"soak-g{i:03d}",
                "size": rng.choice((1, 1, 2)),
                "arrive": cycle,
                "bind_after": rng.randint(2, 5),
                "run_for": rng.randint(8, 24),
            }
        )
        i += 1
    return jobs


def run_soak(
    seed: int = SEED,
    nodes: int = NODES,
    pools: int = POOLS,
    pending_pods: int = PENDING_PODS,
    cycles: int = CYCLES,
    churn: float = CHURN,
):
    """One full soak. Returns (report, flight_records, timeline)."""
    rng = random.Random(seed)

    # ---- planning side: persistent pool-sharded pipeline ---------------
    snapshot = make_steady_cluster(nodes, pools=pools)
    pending = make_steady_pending(pending_pods, pools=pools)
    ages = _ages(pending)
    partition = partition_pools(snapshot, pending)
    pool_snaps = split_snapshot(snapshot, partition)
    pool_pending = split_pending(pending, partition)
    planners = {pool: Planner(_framework()) for pool in partition.pools}
    capacities = node_capacities(pool_snaps.values())
    for pool in partition.pools:
        # The memo structures under leak watch — exactly what the
        # partitioner controller registers in production.
        SIZES.register(
            f"planner.{pool}.verdict_cache",
            lambda p=pool: len(planners[p]._verdict_cache.entries),
        )
        SIZES.register(
            f"planner.{pool}.futility_memo",
            lambda p=pool: len(planners[p]._futility_cache),
        )

    # ---- store side: forecaster + ledger + gang workload ---------------
    store = KubeStore()
    register_indexers(store)
    recorder = FlightRecorder(capacity=65536, seed=seed)
    recorder.attach(store)
    ledger = CapacityLedger(store, flight_recorder=recorder, metrics=False)
    from bench_planner import build_node

    for i in range(STORE_NODES):
        store.create(
            build_node(
                f"soak-w{i:03d}", steady_annotations(False), pool=pool_of(i, pools)
            )
        )
    forecaster = PlacementForecaster(
        store,
        ClusterState(),
        Planner(build_sim_framework(store)),
        TpuSnapshotTaker(),
        capacity_ledger=ledger,
        flight_recorder=recorder,
    )

    # ---- autoscaler decision function on the same virtual clock --------
    spec = ModelServingSpec(
        model=MODEL, slice_profile="2x2", min_replicas=1, max_replicas=4
    )
    as_cfg = AutoscalerConfig()
    now_box = [0.0]
    signals = SignalRegistry(now_fn=lambda: now_box[0])
    replicas = 1
    last_transition = 0.0
    verdict_counts: dict = {}
    transitions = 0

    # ---- the timeline under test ---------------------------------------
    timeline = TimelineStore(
        interval_seconds=CYCLE_S * 2,  # odd cycles sample (A/B interleave)
        clock=lambda: now_box[0],
        policy=DetectorPolicy(
            stall_flat_windows=5,
            # The flight ring grows monotonically by design until its
            # deque bound; a "leak" on it is only real past capacity.
            leak_budgets={"size.record.flight_ring": 65536.0},
        ),
    )
    timeline.attach(flight=recorder)
    WATCHDOG.register("soak-replan", periodic=True, thread_name="soak-replan")

    jobs = build_gang_stream(rng, cycles)
    live: list = []
    variant: dict = {}
    k = max(1, int(nodes * churn))
    replan_sampled: list = []    # replan wall seconds, cycles that tick
    replan_unsampled: list = []  # replan wall seconds, cycles that don't
    tick_durations: list = []
    merge_violations = 0
    incremental_cycles = 0
    forecast_runs = 0
    forecast_stages: dict = {}
    t = 0.0

    # Untimed cold plan: builds every pool's caches at base versions.
    def cold_task(pool):
        def task():
            planners[pool].plan(
                pool_snaps[pool],
                pool_pending[pool],
                dirty=set(pool_snaps[pool].get_nodes()),
                pending_ages=ages,
            )

        return task

    run_pool_plans({p: cold_task(p) for p in partition.pools}, "serial")

    for cycle in range(cycles):
        now_box[0] = t
        WATCHDOG.beat("soak-replan")

        # Gang workload: arrivals, binds, completions against the store.
        for job in [j for j in jobs if j["arrive"] == cycle]:
            job["pods"] = [
                gang_pod(f"{job['name']}-{p}", job["name"], job["size"])
                for p in range(job["size"])
            ]
            for pod in job["pods"]:
                store.create(pod)
            ledger.note_gang_arrival(f"default/{job['name']}", t)
            live.append(job)
        for job in [
            j for j in live
            if "bound_at" not in j and cycle >= j["arrive"] + j["bind_after"]
        ]:
            for idx, pod in enumerate(job["pods"]):
                pod.spec.node_name = f"soak-w{idx:03d}"
                store.update(pod)
            job["bound_at"] = cycle
            ledger.note_gang_bound(f"default/{job['name']}", t)
        for job in [
            j for j in live
            if "bound_at" in j and cycle >= j["bound_at"] + j["run_for"]
        ]:
            for pod in job["pods"]:
                store.delete("Pod", pod.metadata.name, "default")
            live.remove(job)

        # Churn + sharded replan (the timed unit).
        pool_dirty = {pool: set() for pool in partition.pools}
        for j in range(k):
            i = (cycle * k + j) % nodes
            name = node_name(i)
            variant[name] = not variant.get(name, False)
            pool = partition.node_pool[name]
            pool_snaps[pool].refresh_node(
                name, build_steady_node(name, variant[name], pool=pool_of(i, pools))
            )
            pool_dirty[pool].add(name)

        def make_task(pool):
            def task():
                current = pool_snaps[pool].partitioning_state()
                desired = planners[pool].plan(
                    pool_snaps[pool],
                    pool_pending[pool],
                    dirty=pool_dirty[pool],
                    pending_ages=ages,
                )
                return current, desired

            return task

        t0 = time.perf_counter()
        outcomes = run_pool_plans(
            {p: make_task(p) for p in partition.pools}, "serial"
        )
        pool_current = {p: cur for p, (cur, _) in outcomes.items()}
        pool_desired = {p: des for p, (_, des) in outcomes.items()}
        violations = check_merge_invariants(
            partition, pool_current, pool_desired, capacities=capacities
        )
        merge_pool_states(pool_desired)
        replan_s = time.perf_counter() - t0
        merge_violations += len(violations)
        if all(p.last_plan_mode == "incremental" for p in planners.values()):
            incremental_cycles += 1

        # Forecast the pending gangs on cadence (read-only).
        pending_gang_pods = [
            pod for j in live if "bound_at" not in j for pod in j["pods"]
        ]
        if cycle % FORECAST_EVERY == 0 and pending_gang_pods:
            payload = forecaster.run_once(
                now=t,
                pending=pending_gang_pods,
                cycle_seconds=CYCLE_S,
                reconfig_seconds=2.0,
            )
            forecast_runs += 1
            for gang in payload["gangs"]:
                forecast_stages[gang["stage"]] = (
                    forecast_stages.get(gang["stage"], 0) + 1
                )

        # Autoscaler decision on seeded demand.
        signals.note_arrival(
            MODEL, t, queue_depth=rng.choice((0, 1, 2, 4, 8, 16))
        )
        decision = policy.decide(
            spec, replicas, signals.get(MODEL), as_cfg, t,
            last_transition_t=last_transition,
        )
        verdict_counts[decision.verdict] = (
            verdict_counts.get(decision.verdict, 0) + 1
        )
        if decision.desired != replicas:
            replicas = decision.desired
            last_transition = t
            transitions += 1

        # A/B interleave: odd cycles tick the timeline, even cycles do
        # not — the unsampled cycles are the overhead baseline.
        if cycle % 2 == 1:
            t1 = time.perf_counter()
            timeline.tick(now=t)
            tick_durations.append(time.perf_counter() - t1)
            replan_sampled.append(replan_s)
        else:
            replan_unsampled.append(replan_s)

        t = round(t + CYCLE_S, 6)

    # Final heal: drain everything still live, then one last tick so the
    # detectors see the drained steady state.
    now_box[0] = t
    for job in live:
        for pod in job.get("pods", []):
            if store.try_get("Pod", pod.metadata.name, "default") is not None:
                store.delete("Pod", pod.metadata.name, "default")
    WATCHDOG.beat("soak-replan")
    timeline.tick(now=t)
    WATCHDOG.unregister("soak-replan")

    recorder.detach()
    for pool in partition.pools:
        SIZES.unregister(f"planner.{pool}.verdict_cache")
        SIZES.unregister(f"planner.{pool}.futility_memo")
    records = [json.loads(line) for line in recorder.to_jsonl().splitlines()]
    replay = ReplaySession(records).run()

    findings = timeline.findings_payload()["findings"]
    leak_stall = [
        f for f in findings
        if f["detector"] in (detectors.LEAK, detectors.STALL)
    ]
    p50_base = statistics.median(replan_unsampled)
    p50_sampled = statistics.median(replan_sampled)
    p50_tick = statistics.median(tick_durations)
    per_cycle_sampling = sum(tick_durations) / cycles
    if os.environ.get("NOS_SOAK_DEBUG"):
        print(
            f"p50 replan unsampled={p50_base * 1000:.3f}ms "
            f"sampled={p50_sampled * 1000:.3f}ms "
            f"tick={p50_tick * 1000:.3f}ms "
            f"per-cycle sampling={per_cycle_sampling * 1000:.3f}ms",
            file=sys.stderr,
        )
    # Two wall-clock guards, reduced to booleans for bit-stability: the
    # sampling overhead the soak pays per plan cycle (total tick time
    # amortized over all cycles — the sampler fires every 2nd cycle)
    # must stay <= 2% of the steady-state replan p50, and the sampled
    # cycles' replan p50 must not degrade past the same budget (1ms
    # floor absorbs timer noise at these magnitudes).
    sample_within = per_cycle_sampling <= OVERHEAD_BUDGET * p50_base
    ab_within = (p50_sampled - p50_base) <= max(
        OVERHEAD_BUDGET * p50_base, 0.001
    )
    report = {
        "workload": {
            "seed": seed,
            "nodes": nodes,
            "pools": pools,
            "pending_pods": pending_pods,
            "cycles": cycles,
            "churn": churn,
            "gangs": len(jobs),
            "store_nodes": STORE_NODES,
        },
        "planning": {
            "incremental_cycles": incremental_cycles,
            "merge_violations": merge_violations,
        },
        "autoscaler": {
            "decisions": cycles,
            "transitions": transitions,
            "final_replicas": replicas,
            "verdicts": dict(sorted(verdict_counts.items())),
        },
        "forecast": {
            "runs": forecast_runs,
            "stages": dict(sorted(forecast_stages.items())),
        },
        "timeline": {
            "samples": timeline.samples,
            "findings": findings,
            "leak_stall_findings": len(leak_stall),
            "clean_after_final_heal": not leak_stall,
        },
        "overhead": {
            "budget": OVERHEAD_BUDGET,
            "sample_within_budget": sample_within,
            "ab_interleave_within_budget": ab_within,
        },
        "replay": {
            "records": len(records),
            "timeline_findings": replay.timeline_findings,
            "drifts": len(replay.drifts),
            "ok": replay.ok(),
        },
    }
    return report, records, timeline


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=SEED)
    parser.add_argument("--output", default="")
    args = parser.parse_args()
    report, _, _ = run_soak(args.seed)
    text = json.dumps(report, indent=1, sort_keys=True) + "\n"
    if args.output:
        with open(args.output, "w") as fh:
            fh.write(text)
    print(text, end="")
    failures = []
    if report["planning"]["incremental_cycles"] != report["workload"]["cycles"]:
        failures.append("a replan cycle fell off the incremental path")
    if report["planning"]["merge_violations"] != 0:
        failures.append("cross-pool merge invariants violated")
    if not report["timeline"]["clean_after_final_heal"]:
        failures.append("leak/stall finding after final heal")
    if not report["overhead"]["sample_within_budget"]:
        failures.append("per-cycle sampling overhead exceeds 2% of replan p50")
    if not report["overhead"]["ab_interleave_within_budget"]:
        failures.append("sampled cycles' replan p50 degraded past budget")
    if not report["replay"]["ok"]:
        failures.append("replay drift")
    for failure in failures:
        print(f"FAIL: {failure}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
