"""Placement-forecaster benchmark: calibration on a streaming workload.

A seeded BENCH_r05-style stream — mixed 4- and 8-chip gangs plus 2-chip
singletons arriving over ~2 virtual minutes — runs against a small carved
cluster on a pure virtual clock. Every cycle the REAL forecaster
(engine + advisor + accuracy join, via ``run_once`` with an explicit
``now``) forecasts the pending queue; then a deterministic reference
scheduler binds what fits, starts a re-carve of spare capacity when the
queue demands it, and completes jobs on schedule. Running pods carry
honest ``expected-completion`` hints, so blocked-stage ETAs are priced
the way a cooperative workload would price them.

Arrival -> bind joins flow through a real CapacityLedger gang-bound
listener — the same path production uses — so the calibration payload in
the report is the auditor's own p50/p95, not a bench-side recompute. The
acceptance gate: p95 absolute ETA error <= 25% of the gang's actual wait.

Determinism: every number derives from the seed and the virtual clock.
The forecaster never writes to the store (asserted every cycle) and the
virtual clock never advances while it runs, so forecast overhead on the
virtual timeline is zero by construction; the wall-clock <=2% replan
budget is enforced separately by tests/partitioning/test_planner_perf.py.
The committed BENCH_forecast.json is byte-identical across runs.

  make bench-forecast
  python bench_forecast.py --output BENCH_forecast.json
"""
from __future__ import annotations

import argparse
import json
import random

from nos_tpu.api.v1alpha1 import annotations as annot
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.capacity.ledger import CapacityLedger
from nos_tpu.cmd.partitioner import build_sim_framework, register_indexers
from nos_tpu.forecast import EXPECTED_COMPLETION_ANNOTATION, PlacementForecaster
from nos_tpu.kube.objects import Container, Node, NodeStatus, ObjectMeta, Pod, PodPhase, PodSpec
from nos_tpu.kube.store import KubeStore
from nos_tpu.partitioning.core import ClusterState, Planner
from nos_tpu.partitioning.tpu import TpuSnapshotTaker
from nos_tpu.record import FlightRecorder
from nos_tpu.record.replay import ReplaySession
from nos_tpu.scheduler.plugins.gang import GANG_NAME_LABEL, GANG_SIZE_LABEL

SEED = 5
CYCLE_S = 1.0  # virtual scheduler cadence: feasible-now binds next tick
RECONFIG_S = 2.0  # virtual re-carve actuation latency
HORIZON_S = 400.0  # hard stop; the stream drains well before this
GANG_PROFILE = "2x2"  # 4 chips
SMALL_PROFILE = "1x2"  # 2 chips
ACCURACY_TARGET_P95_RATIO = 0.25


def tpu_node(name: str, free, used) -> Node:
    alloc = {constants.RESOURCE_TPU: 8, "cpu": 8, "memory": 128}
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={
                labels.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
                labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
                labels.PARTITIONING_LABEL: "tpu",
            },
            annotations=annot.status_from_devices(free=free, used=used),
        ),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def make_pod(name: str, profile: str, gang: str = "", size: int = 0) -> Pod:
    pod = Pod(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PodSpec(
            containers=[
                Container(requests={constants.tpu_slice_resource(profile): 1})
            ],
            scheduler_name=constants.SCHEDULER_NAME,
        ),
    )
    if gang:
        pod.metadata.labels[GANG_NAME_LABEL] = gang
        pod.metadata.labels[GANG_SIZE_LABEL] = str(size)
    return pod


class SimNode:
    """Bench-side geometry ledger for one node; mirrored into the store's
    node annotations after every mutation."""

    def __init__(self, store, name: str, free=None, carved=True):
        self.store = store
        self.name = name
        self.carved = carved
        self.free = dict(free or {})
        self.used: dict = {}
        self.sync()

    def sync(self) -> None:
        if self.carved:
            node = tpu_node(self.name, {0: self.free}, {0: self.used})
        else:
            node = tpu_node(self.name, {}, {})
        if self.store.try_get("Node", self.name) is None:
            self.store.create(node)
        else:
            self.store.update(node)

    def carve(self, free) -> None:
        self.carved = True
        self.free = dict(free)
        self.used = {}
        self.sync()

    def take(self, profile: str) -> None:
        self.free[profile] -= 1
        if self.free[profile] == 0:
            del self.free[profile]
        self.used[profile] = self.used.get(profile, 0) + 1
        self.sync()

    def release(self, profile: str) -> None:
        self.used[profile] -= 1
        if self.used[profile] == 0:
            del self.used[profile]
        self.free[profile] = self.free.get(profile, 0) + 1
        self.sync()


def build_workload(rng: random.Random):
    """An r05-flavoured stream: bursty arrivals, mixed gang widths, a
    tail of 2-chip singletons backfilling around them."""
    jobs = []
    t = 0.0
    for i in range(40):
        t += rng.expovariate(1.0 / 2.2)
        size = rng.choice((1, 1, 2))  # 4-chip jobs outnumber 8-chip ones
        jobs.append(
            {
                "kind": "gang",
                "name": f"g{i:02d}",
                "size": size,
                "arrival": round(t, 3),
                # Whole-cycle runtimes: completions land exactly on the
                # scheduler grid, like a cooperative trainer checkpointing
                # on step boundaries.
                "runtime": float(rng.randrange(8, 21)),
            }
        )
    t = 2.0
    for i in range(12):
        t += rng.expovariate(1.0 / 9.0)
        jobs.append(
            {
                "kind": "small",
                "name": f"s{i:02d}",
                "arrival": round(t, 3),
                "runtime": float(rng.randrange(3, 9)),
            }
        )
    return sorted(jobs, key=lambda j: (j["arrival"], j["name"]))


def run_bench(seed: int = SEED):
    """One full stream run. Returns (report, flight_records)."""
    store = KubeStore()
    register_indexers(store)
    recorder = FlightRecorder()
    recorder.attach(store)
    ledger = CapacityLedger(store, flight_recorder=recorder, metrics=False)

    # 2 nodes pre-carved for gangs, 1 mixed node whose 1x2 slivers host
    # the singletons (and feed the backfill-safety trials), 1 uncarved
    # spare the reference scheduler re-carves on demand. Sized so the
    # stream saturates: gangs queue, block, and ride the re-carve.
    nodes = {
        name: SimNode(store, name, free={GANG_PROFILE: 2})
        for name in ("w0", "w1")
    }
    nodes["w3"] = SimNode(
        store, "w3", free={SMALL_PROFILE: 2, GANG_PROFILE: 1}
    )
    nodes["spare0"] = SimNode(store, "spare0", carved=False)

    forecaster = PlacementForecaster(
        store,
        ClusterState(),
        Planner(build_sim_framework(store)),
        TpuSnapshotTaker(),
        capacity_ledger=ledger,
        flight_recorder=recorder,
    )

    jobs = build_workload(random.Random(seed))
    queue: list = []  # live job dicts, FIFO by (arrival, name)
    carve_done_at = None
    stage_counts: dict = {}
    advisor_validated_cycles = 0
    advisor_example = None
    max_savings = 0.0
    forecast_store_writes = 0
    waits = []
    t = 0.0
    cycles = 0

    def free_count(profile):
        return sum(n.free.get(profile, 0) for n in nodes.values())

    def bind(job, profile, now):
        placements = []
        for pod in job["pods"]:
            target = next(
                name
                for name in sorted(nodes)
                if nodes[name].free.get(profile, 0) > 0
            )
            nodes[target].take(profile)
            pod.spec.node_name = target
            pod.status.phase = PodPhase.RUNNING
            pod.metadata.annotations[EXPECTED_COMPLETION_ANNOTATION] = str(
                now + job["runtime"]
            )
            store.update(pod)
            placements.append(target)
        job["ends_at"] = now + job["runtime"]
        job["bound_at"] = now
        if job["kind"] == "gang":
            ledger.note_gang_bound(f"default/{job['name']}", now)
            waits.append(round(now - job["arrival"], 6))

    while t < HORIZON_S:
        # 1. Binds, on LAST cycle's capacity: a pod forecast feasible-now
        #    at tick T binds at T+1 — exactly the engine's cycle_seconds
        #    pricing. Greedy FIFO (later jobs backfill around an
        #    infeasible head).
        for job in sorted(
            [j for j in queue if "bound_at" not in j],
            key=lambda j: (j["arrival"], j["name"]),
        ):
            profile = GANG_PROFILE if job["kind"] == "gang" else SMALL_PROFILE
            if free_count(profile) >= len(job["pods"]):
                bind(job, profile, t)
        # 2. Re-carve actuation + completions land on this tick; the
        #    freed capacity binds next tick, matching the engine's
        #    "completion + one plan cycle" blocked-stage pricing.
        if carve_done_at is not None and carve_done_at <= t:
            nodes["spare0"].carve({GANG_PROFILE: 2})
            carve_done_at = None
        for job in [j for j in queue if j.get("ends_at", HORIZON_S + 1) <= t]:
            profile = GANG_PROFILE if job["kind"] == "gang" else SMALL_PROFILE
            for pod in job["pods"]:
                nodes[pod.spec.node_name].release(profile)
                store.delete("Pod", pod.metadata.name, "default")
            queue.remove(job)
        # 3. Arrivals.
        while jobs and jobs[0]["arrival"] <= t:
            job = jobs.pop(0)
            size = job.get("size", 1)
            if job["kind"] == "gang":
                job["pods"] = [
                    make_pod(
                        f"{job['name']}-{k}", GANG_PROFILE,
                        gang=job["name"], size=size,
                    )
                    for k in range(size)
                ]
                ledger.note_gang_arrival(f"default/{job['name']}", t)
            else:
                job["pods"] = [make_pod(job["name"], SMALL_PROFILE)]
            for pod in job["pods"]:
                store.create(pod)
            queue.append(job)
        # 4. Re-carve kick for a backed-up gang queue.
        backlog = [j for j in queue if "bound_at" not in j and j["kind"] == "gang"]
        if backlog and not nodes["spare0"].carved and carve_done_at is None:
            carve_done_at = t + RECONFIG_S
        # 5. Forecast the still-pending queue (read-only; zero writes).
        pending = [
            pod for j in queue if "bound_at" not in j for pod in j["pods"]
        ]
        if pending:
            revision = store.revision
            payload = forecaster.run_once(
                now=t,
                pending=pending,
                cycle_seconds=CYCLE_S,
                reconfig_seconds=RECONFIG_S,
            )
            forecast_store_writes += store.revision - revision
            for gang in payload["gangs"]:
                stage_counts[gang["stage"]] = (
                    stage_counts.get(gang["stage"], 0) + 1
                )
            advisor = payload["advisor"] or {}
            if advisor.get("validated"):
                advisor_validated_cycles += 1
                savings = advisor["predicted_idle_savings_chip_seconds"]
                if savings > max_savings:
                    max_savings = savings
                if advisor_example is None:
                    advisor_example = {
                        "cycle": cycles,
                        "proposals": advisor["proposals"],
                        "predicted_idle_savings_chip_seconds": savings,
                    }
        cycles += 1
        t = round(t + CYCLE_S, 6)
        if not jobs and not queue:
            break

    recorder.detach()
    records = [json.loads(line) for line in recorder.to_jsonl().splitlines()]
    replay = ReplaySession(records).run()
    calibration = forecaster.calibration.payload()
    meets = (
        calibration["p95_ratio"] is not None
        and calibration["p95_ratio"] <= ACCURACY_TARGET_P95_RATIO
    )
    waits_sorted = sorted(waits)
    report = {
        "workload": {
            "seed": seed,
            "gangs": sum(1 for w in waits),
            "smalls": 12,
            "cycles": cycles,
            "wait_seconds": {
                "p50": waits_sorted[len(waits_sorted) // 2],
                "max": waits_sorted[-1],
            },
        },
        "stages": stage_counts,
        "accuracy": {
            **calibration,
            "target_p95_ratio": ACCURACY_TARGET_P95_RATIO,
            "meets_target": meets,
        },
        "backfill": {"unsafe_total": forecaster.backfill_unsafe_total},
        "advisor": {
            "validated_cycles": advisor_validated_cycles,
            "max_predicted_savings_chip_seconds": max_savings,
            "example": advisor_example,
        },
        "overhead": {
            "budget": 0.02,
            "within_budget": True,
            "forecast_store_writes": forecast_store_writes,
        },
        "replay": {
            "records": len(records),
            "forecast_cycles": replay.forecast_cycles,
            "forecast_outcomes": replay.forecast_outcomes,
            "drifts": len(replay.drifts),
            "ok": replay.ok(),
        },
    }
    return report, records


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=SEED)
    parser.add_argument("--output", default="")
    args = parser.parse_args()
    report, _ = run_bench(args.seed)
    text = json.dumps(report, indent=1, sort_keys=True) + "\n"
    if args.output:
        with open(args.output, "w") as fh:
            fh.write(text)
    print(text, end="")
    failures = []
    if not report["accuracy"]["meets_target"]:
        failures.append("p95 ETA error exceeds 25% of actual wait")
    if report["advisor"]["validated_cycles"] < 1:
        failures.append("no advisor recommendation validated by shadow sim")
    if report["overhead"]["forecast_store_writes"] != 0:
        failures.append("forecaster wrote to the store")
    if not report["replay"]["ok"]:
        failures.append("replay drift")
    for failure in failures:
        print(f"FAIL: {failure}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
