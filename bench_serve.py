"""Serving SLO benchmark: open-loop workload against the real engine.

Drives the continuous-batching engine (tiny CPU llama, real jitted
prefill/decode programs) with a seeded Poisson arrival stream — hot/cold
model skew, diurnal rate shaping — on a VIRTUAL cost-model clock
(slo/driver.py): every latency in the report is a pure function of the
seed, the workload config, and the engine's scheduling decisions, so the
committed BENCH_serve.json is bit-stable across runs and machines.

What it measures (and the autoscaler of ROADMAP item 3 will consume):

  ttft_s / tpot_s / e2e_s / queue_wait_s  — p50/p95/p99, per model + aggregate
  goodput                                 — requests/tokens that met the
                                            SLO-derived latency targets
  slo.verdicts                            — per-SLO fast/slow burn rate,
                                            compliance, budget remaining

The default workload is sized to stress the 4-slot replica at its
diurnal peak (~96% of token capacity) so queue waits and SLO burn are
visible, without tipping into unbounded backlog.

  make bench-serve
  python bench_serve.py --smoke          # the serve-smoke tier's config
  python bench_serve.py --output BENCH_serve.json
"""
from __future__ import annotations

import argparse
import json

import jax

from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.serve.engine import Engine
from nos_tpu.serve.telemetry import ServeTelemetry, VirtualServeClock
from nos_tpu.slo.driver import ModelProfile, OpenLoopDriver, WorkloadConfig
from nos_tpu.slo.engine import SLOEngine

# The committed default objectives. With the virtual cost model (8 ms
# per batched decode tick, 0.2 ms per prefill token) TPOT is ~8 ms and
# an unqueued TTFT is ~75 ms (prefill + the first decode chunk's sync),
# so the headroom in these thresholds is what the diurnal peak's
# queueing eats into.
DEFAULT_SLOS = (
    "p95 ttft < 500ms",
    "p99 e2e < 3s",
    "p50 tpot < 20ms",
    "availability 99%",
)


def build_engines(config: WorkloadConfig, slo: SLOEngine):
    """One tiny-llama replica per model profile, all sharing one weight
    init (the skew under test is traffic, not parameters), each on its
    own virtual clock with goodput targets derived from the SLO specs."""
    model_config = tiny_config()
    params = init_llama_params(jax.random.key(0), model_config)
    targets = slo.latency_targets()
    engines = {}
    for profile in config.models:
        telemetry = ServeTelemetry(
            model=profile.name,
            clock=VirtualServeClock(),
            ttft_target_s=targets.get("ttft"),
            e2e_target_s=targets.get("e2e"),
            on_complete=slo.record,
        )
        engines[profile.name] = Engine(
            params,
            model_config,
            max_slots=4,
            max_len=256,
            ticks_per_sync=8,
            # prompts above 16 tokens take the chunked-admission path, so
            # the bench exercises both prefill paths every run
            prefill_chunk=16,
            model=profile.name,
            telemetry=telemetry,
        )
    return engines


def run(args: argparse.Namespace) -> dict:
    config = WorkloadConfig(
        seed=args.seed,
        duration_s=args.duration,
        rate_rps=args.rate,
        diurnal_amplitude=0.5,
        diurnal_period_s=args.duration,
        models=(
            ModelProfile(
                name="hot", weight=0.8, prompt_tokens=(8, 32),
                max_new_tokens=(8, 48),
            ),
            ModelProfile(
                name="cold", weight=0.2, prompt_tokens=(8, 32),
                max_new_tokens=(8, 48),
            ),
        ),
    )
    slo = SLOEngine(
        list(args.slo),
        fast_window_s=args.duration / 4.0,
        slow_window_s=args.duration * 2.0,
    )
    engines = build_engines(config, slo)
    driver = OpenLoopDriver(engines, config, slo=slo)
    return driver.run()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--duration", type=float, default=120.0,
        help="virtual seconds of arrivals",
    )
    parser.add_argument(
        "--rate", type=float, default=8.0,
        help="mean arrival rate (requests / virtual second)",
    )
    parser.add_argument(
        "--slo", action="append", default=None,
        help="SLO spec (repeatable); default: %s" % (DEFAULT_SLOS,),
    )
    parser.add_argument("--output", default=None, help="write JSON here")
    parser.add_argument(
        "--smoke", action="store_true",
        help="tiny config for the serve-smoke tier (~60 requests)",
    )
    args = parser.parse_args()
    if args.slo is None:
        args.slo = list(DEFAULT_SLOS)
    if args.smoke:
        args.duration = min(args.duration, 20.0)
        args.rate = min(args.rate, 3.0)
    report = run(args)
    body = json.dumps(report, indent=2, sort_keys=True)
    if args.output:
        with open(args.output, "w") as f:
            f.write(body + "\n")
    print(body)


if __name__ == "__main__":
    main()
