"""KubeStore microbenchmark: the control plane's shared-state hot paths.

Every controller in the suite reads and writes ONE in-memory store; at
10k nodes / 100k pods the store's list/index/patch/fan-out costs ARE the
control plane's saturation profile, and the 100k-node / 1M-pod config is
the ceiling the multi-process planning work is sized against (repeats
adapt down there — the full-copy list alone is tens of seconds per call,
and the row exists to document that cliff, not to average it). This bench measures the verbs the
loops actually hit, over synthetic clusters shaped like the planner
benches (bound pods round-robin across nodes, a pending residue):

  list            — full-kind list, copy and copy=False (the planner's view)
  list_by_index   — the maintained per-(kind, index) map ("indexed" rows)
                    AND the pre-index full-scan equivalent, replicated as
                    list(filter_fn=...) ("scan" rows) so BENCH_store.json
                    carries the before/after pair for the same store
  patch           — patch_merge status flips on sampled pods (the kubelet
                    and quota controllers' write shape)
  watch_fanout    — W writes fanned out to N subscribed watchers, drained
                    (events delivered / sec end-to-end)
  apply_event     — the flight-replay verb: recorded MODIFIED events
                    re-applied verbatim

Output: one JSON line per (bench, nodes, pods, ...) config, e.g.

  make bench-store
  python bench_store.py --quick
  python bench_store.py --output BENCH_store.json
"""
from __future__ import annotations

import argparse
import json
import queue
import statistics
import time

from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
)
from nos_tpu.kube.store import KubeStore

V5E = "tpu-v5-lite-podslice"


def build_node(name: str) -> Node:
    alloc = {constants.RESOURCE_TPU: 8, "cpu": 8, "memory": 128}
    return Node(
        metadata=ObjectMeta(
            name=name,
            labels={
                labels.GKE_TPU_ACCELERATOR_LABEL: V5E,
                labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
                labels.PARTITIONING_LABEL: "tpu",
            },
        ),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def build_pod(name: str, node: str, phase: str) -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, namespace="bench"),
        spec=PodSpec(
            containers=[Container(requests={constants.RESOURCE_TPU: 1})],
            scheduler_name=constants.SCHEDULER_NAME,
            node_name=node,
        ),
        status=PodStatus(phase=phase),
    )


def seed_store(n_nodes: int, n_pods: int) -> KubeStore:
    """Nodes plus pods bound round-robin; every 10th pod is an unbound
    Pending straggler (the population the partitioner's phase index
    serves). Indexers registered before seeding, like the suite does."""
    store = KubeStore()
    store.add_indexer("Pod", constants.INDEX_POD_PHASE, lambda p: [p.status.phase])
    store.add_indexer("Pod", constants.INDEX_POD_NODE, lambda p: [p.spec.node_name])
    for i in range(n_nodes):
        store.create(build_node(f"node-{i:05d}"))
    for i in range(n_pods):
        if i % 10 == 0:
            store.create(build_pod(f"pod-{i:06d}", "", "Pending"))
        else:
            store.create(
                build_pod(f"pod-{i:06d}", f"node-{i % n_nodes:05d}", "Running")
            )
    return store


def _time_repeats(fn, repeats: int):
    """(total_seconds, per-repeat durations) for `repeats` calls of fn."""
    durations = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        durations.append(time.perf_counter() - t0)
    return sum(durations), durations


def _row(bench: str, n_nodes: int, n_pods: int, **extra) -> dict:
    return {"bench": bench, "nodes": n_nodes, "pods": n_pods, **extra}


def bench_list(store, n_nodes, n_pods, repeats):
    rows = []
    for copy_flag in (True, False):
        total, durations = _time_repeats(
            lambda: store.list("Pod", copy=copy_flag), repeats
        )
        rows.append(
            _row(
                "store_list",
                n_nodes,
                n_pods,
                copy=copy_flag,
                p50_ms=round(statistics.median(durations) * 1e3, 3),
                lists_per_sec=round(repeats / total, 1),
            )
        )
    return rows


def bench_list_by_index(store, n_nodes, n_pods, repeats):
    """The satellite's before/after pair: 'indexed' is the maintained
    index map, 'scan' replicates the pre-index behavior (a full-kind
    scan with a per-object filter) against the very same store."""
    node_fn = lambda p: [p.spec.node_name]  # noqa: E731 — mirrors the indexer
    targets = [f"node-{i:05d}" for i in range(0, n_nodes, max(1, n_nodes // 50))]

    def indexed():
        for node in targets:
            store.list_by_index("Pod", constants.INDEX_POD_NODE, node, copy=False)

    def scan():
        for node in targets:
            store.list("Pod", filter_fn=lambda o: node in node_fn(o), copy=False)

    rows = []
    for variant, fn in (("indexed", indexed), ("scan", scan)):
        # The scan variant is O(pods) per lookup — one repeat suffices to
        # document the collapse at 100k pods.
        reps = repeats if variant == "indexed" else 1
        total, durations = _time_repeats(fn, reps)
        lookups = reps * len(targets)
        rows.append(
            _row(
                "store_list_by_index",
                n_nodes,
                n_pods,
                variant=variant,
                lookups=lookups,
                p50_lookup_ms=round(
                    statistics.median(durations) / len(targets) * 1e3, 4
                ),
                lookups_per_sec=round(lookups / total, 1),
            )
        )
    return rows


def bench_patch(store, n_nodes, n_pods, repeats):
    sampled = [f"pod-{i:06d}" for i in range(1, min(n_pods, 2000), 7)]

    def flip(p):
        p.status.phase = "Running" if p.status.phase == "Pending" else "Pending"

    def patch_all():
        for name in sampled:
            store.patch_merge("Pod", name, "bench", flip)

    total, _ = _time_repeats(patch_all, repeats)
    patches = repeats * len(sampled)
    return [
        _row(
            "store_patch",
            n_nodes,
            n_pods,
            patches=patches,
            patches_per_sec=round(patches / total, 1),
        )
    ]


def bench_watch_fanout(store, n_nodes, n_pods, n_watchers, writes):
    queues = [
        store.watch({"Pod"}, name=f"bench-watcher-{i}") for i in range(n_watchers)
    ]
    # Drain the ADDED replay so only the bench's own writes are measured.
    for q in queues:
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break

    def bump(p):
        p.status.phase = p.status.phase  # rv bump; field content irrelevant

    t0 = time.perf_counter()
    for i in range(writes):
        store.patch_merge("Pod", f"pod-{i % n_pods:06d}", "bench", bump)
    delivered = 0
    for q in queues:
        while True:
            try:
                q.get_nowait()
                delivered += 1
            except queue.Empty:
                break
    total = time.perf_counter() - t0
    for q in queues:
        store.stop_watch(q)
    return [
        _row(
            "store_watch_fanout",
            n_nodes,
            n_pods,
            watchers=n_watchers,
            writes=writes,
            events_delivered=delivered,
            events_per_sec=round(delivered / total, 1),
        )
    ]


def bench_apply_event(store, n_nodes, n_pods, events):
    # Replay-shaped traffic: re-apply MODIFIED snapshots of live pods
    # verbatim (deepcopy inside apply_event is part of the measured cost,
    # exactly as replay pays it).
    pods = store.list("Pod", copy=False)[: min(events, n_pods)]
    t0 = time.perf_counter()
    applied = 0
    while applied < events:
        for pod in pods:
            store.apply_event("MODIFIED", pod)
            applied += 1
            if applied >= events:
                break
    total = time.perf_counter() - t0
    return [
        _row(
            "store_apply_event",
            n_nodes,
            n_pods,
            events=events,
            events_per_sec=round(applied / total, 1),
        )
    ]


def run_config(n_nodes: int, n_pods: int, n_watchers: int, quick: bool):
    t0 = time.perf_counter()
    store = seed_store(n_nodes, n_pods)
    seed_s = time.perf_counter() - t0
    rows = [
        _row(
            "store_seed",
            n_nodes,
            n_pods,
            seed_seconds=round(seed_s, 2),
            creates_per_sec=round((n_nodes + n_pods) / seed_s, 1),
        )
    ]
    # Adaptive repeats: at 1M pods a single copy=True list is tens of
    # seconds — two repeats document the number without an hour-long run,
    # and the committed 10k rows keep their 5-repeat medians unchanged.
    repeats = 2 if quick or n_pods >= 1_000_000 else 5
    rows += bench_list(store, n_nodes, n_pods, repeats)
    rows += bench_list_by_index(store, n_nodes, n_pods, repeats)
    rows += bench_patch(store, n_nodes, n_pods, repeats)
    rows += bench_watch_fanout(
        store, n_nodes, n_pods, n_watchers, writes=200 if quick else 1000
    )
    rows += bench_apply_event(store, n_nodes, n_pods, events=500 if quick else 5000)
    return rows


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--configs",
        default="1000x10000,10000x100000,100000x1000000",
        help="comma-separated nodesxpods pairs",
    )
    parser.add_argument("--watchers", type=int, default=8)
    parser.add_argument(
        "--quick", action="store_true", help="100x1000 only, fewer repeats"
    )
    parser.add_argument("--output", default="", help="also append JSON lines to file")
    args = parser.parse_args()

    configs = [tuple(map(int, c.split("x"))) for c in args.configs.split(",")]
    if args.quick:
        configs = [(100, 1000)]

    results = []
    for n_nodes, n_pods in configs:
        for row in run_config(n_nodes, n_pods, args.watchers, args.quick):
            results.append(row)
            print(json.dumps(row), flush=True)

    if args.output:
        with open(args.output, "a") as fh:
            for row in results:
                fh.write(json.dumps(row) + "\n")


if __name__ == "__main__":
    main()
