"""Local sharing-comparison harness: contention curves on one accelerator.

Mirrors the reference's experiment (demos/gpu-sharing-comparison/README.md):
average inference time of a small vision model vs number of workloads
sharing one device, under each sharing discipline this framework's
partitioner can actuate:

- ``time-shared``  N workers submit concurrently to the same device with no
  isolation — latency degrades roughly linearly with N (the reference's
  time-slicing row).
- ``partitioned``  each worker runs in its own exclusive turn, modeling the
  hard isolation a carved slice / HBM fraction gives — per-inference
  latency stays flat regardless of N (the reference's MIG row; real
  slice isolation needs the operator on a cluster, see README).

Usage: python harness.py [--pods 1,3,5,7] [--seconds 5]
Prints a markdown table like the reference's results table.
"""
from __future__ import annotations

import argparse
import statistics
import sys
import threading
import time


def build_infer():
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, __file__.rsplit("/demos/", 1)[0])
    from nos_tpu.models.resnet import (
        init_resnet_params,
        resnet_forward,
        tiny_resnet_config,
    )

    config = tiny_resnet_config()
    params = init_resnet_params(jax.random.key(0), config)
    images = jnp.zeros((8, 224, 224, 3), jnp.float32)
    infer = jax.jit(lambda x: resnet_forward(params, x, config))
    jax.block_until_ready(infer(images))
    return jax, infer, images


def timed_loop(jax, infer, images, stop_at: float, out: list) -> None:
    while time.monotonic() < stop_at:
        start = time.monotonic()
        jax.block_until_ready(infer(images))
        out.append(time.monotonic() - start)


def run_time_shared(jax, infer, images, n: int, seconds: float) -> float:
    """N concurrent workers contending for the device."""
    stop_at = time.monotonic() + seconds
    results: list = [[] for _ in range(n)]
    threads = [
        threading.Thread(target=timed_loop, args=(jax, infer, images, stop_at, results[i]))
        for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    all_lat = [x for r in results for x in r]
    return statistics.fmean(all_lat) if all_lat else float("nan")


def run_partitioned(jax, infer, images, n: int, seconds: float) -> float:
    """Each worker gets an exclusive, isolated execution turn."""
    all_lat: list = []
    for _ in range(n):
        out: list = []
        timed_loop(jax, infer, images, time.monotonic() + seconds / n, out)
        all_lat.extend(out)
    return statistics.fmean(all_lat) if all_lat else float("nan")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--pods", default="1,3,5,7")
    parser.add_argument("--seconds", type=float, default=5.0)
    args = parser.parse_args()
    pod_counts = [int(x) for x in args.pods.split(",")]

    jax, infer, images = build_infer()
    print(f"backend: {jax.default_backend()}", file=sys.stderr)

    rows = {}
    for mode, runner in (("time-shared", run_time_shared), ("partitioned", run_partitioned)):
        rows[mode] = {}
        for n in pod_counts:
            rows[mode][n] = runner(jax, infer, images, n, args.seconds)
            print(f"{mode} x{n}: {rows[mode][n]:.4f}s", file=sys.stderr)

    header = "| mode | " + " | ".join(f"{n} pods" for n in pod_counts) + " |"
    sep = "|---" * (len(pod_counts) + 1) + "|"
    print(header)
    print(sep)
    for mode in rows:
        cells = " | ".join(f"{rows[mode][n]:.4f}" for n in pod_counts)
        print(f"| {mode} | {cells} |")


if __name__ == "__main__":
    main()
