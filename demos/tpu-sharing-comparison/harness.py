"""Local sharing-comparison harness: contention curves on one machine.

Mirrors the reference's experiment (demos/gpu-sharing-comparison/README.md):
average inference time of a small vision model vs number of workloads
sharing one device, under two sharing disciplines. Both disciplines run
REAL concurrent OS processes — nothing takes turns under a lock — so the
contention column measures actual interference, not a modeling assumption:

- ``time-shared``  N worker processes all scheduled over the SAME full
  compute resource (every core) with no isolation; they interfere freely
  — the reference's time-slicing row, latency grows with N.
- ``partitioned``  each worker process is pinned to its own EXCLUSIVE,
  fixed-size core set (``sched_setaffinity``; size = cores / max pods) —
  the local stand-in for a carved slice's hard isolation: per-inference
  latency stays flat regardless of how many neighbors exist, because the
  neighbors physically cannot touch the worker's cores. Real TPU slice /
  HBM-fraction isolation needs the operator on a cluster (README).

Usage: python harness.py [--pods 1,3,5,7] [--seconds 5]
Prints a markdown table like the reference's results table.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

REPO_ROOT = __file__.rsplit("/demos/", 1)[0]


# ------------------------------------------------------------------ worker


def run_worker() -> None:
    """One benchmark pod: pin to NOS_DEMO_CORES (if set), run the
    inference loop for NOS_DEMO_SECONDS, print a JSON latency line."""
    cores = os.environ.get("NOS_DEMO_CORES", "")
    if cores:
        os.sched_setaffinity(0, {int(c) for c in cores.split(",")})
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    sys.path.insert(0, REPO_ROOT)
    from nos_tpu.models.resnet import (
        init_resnet_params,
        resnet_forward,
        tiny_resnet_config,
    )

    config = tiny_resnet_config()
    params = init_resnet_params(jax.random.key(0), config)
    images = jnp.zeros((8, 224, 224, 3), jnp.float32)
    infer = jax.jit(lambda x: resnet_forward(params, x, config))
    jax.block_until_ready(infer(images))  # compile outside the window

    seconds = float(os.environ.get("NOS_DEMO_SECONDS", "5"))
    # Ready/go handshake: compile time varies wildly between workers (and
    # grows under contention), so the parent must release the barrier only
    # after EVERY worker has finished compiling — otherwise the windows
    # barely overlap and the contention column measures near-solo latency.
    print("READY", flush=True)
    sys.stdin.readline()  # parent writes GO once all workers are ready
    latencies = []
    stop_at = time.monotonic() + seconds
    while time.monotonic() < stop_at:
        start = time.monotonic()
        jax.block_until_ready(infer(images))
        latencies.append(time.monotonic() - start)
    print(json.dumps({"n": len(latencies), "mean_s": statistics.fmean(latencies) if latencies else None}))


# ----------------------------------------------------------------- parent


def launch(n: int, seconds: float, core_sets) -> float:
    """Spawn n REAL processes, one per core set (None = unpinned); release
    them simultaneously once all report READY; average their means."""
    procs = []
    for i in range(n):
        env = {**os.environ, "NOS_DEMO_SECONDS": str(seconds)}
        if core_sets is not None:
            env["NOS_DEMO_CORES"] = ",".join(str(c) for c in core_sets[i])
        procs.append(
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--worker"],
                env=env,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
            )
        )
    # Barrier: wait for every worker's READY (compile done), then GO all.
    for i, p in enumerate(procs):
        line = p.stdout.readline().decode().strip()
        if line != "READY":
            raise RuntimeError(
                f"worker {i} (pid {p.pid}) failed before READY "
                f"(rc={p.poll()}): {line!r} — see its stderr above"
            )
    for p in procs:
        p.stdin.write(b"GO\n")
        p.stdin.flush()
    means = []
    for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=seconds + 120)
        lines = out.decode().strip().splitlines()
        if p.returncode != 0 or not lines:
            raise RuntimeError(
                f"worker {i} (pid {p.pid}) died rc={p.returncode} with no "
                f"report — see its stderr above"
            )
        report = json.loads(lines[-1])
        if report["mean_s"] is not None:
            means.append(report["mean_s"])
    return statistics.fmean(means) if means else float("nan")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--pods", default="1,3,5,7")
    parser.add_argument("--seconds", type=float, default=5.0)
    parser.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    args = parser.parse_args()
    if args.worker:
        return run_worker()
    pod_counts = [int(x) for x in args.pods.split(",")]

    cores = sorted(os.sched_getaffinity(0))
    slice_size = max(1, len(cores) // max(pod_counts))
    print(
        f"{len(cores)} cores; partitioned slice = {slice_size} exclusive cores/pod",
        file=sys.stderr,
    )
    if len(cores) < max(pod_counts):
        print(
            f"WARNING: only {len(cores)} cores for up to {max(pod_counts)} pods — "
            "slices must overlap, so the partitioned row cannot demonstrate "
            "isolation on this machine",
            file=sys.stderr,
        )

    rows = {}
    for mode in ("time-shared", "partitioned"):
        rows[mode] = {}
        for n in pod_counts:
            if mode == "partitioned":
                core_sets = [
                    [
                        cores[(i * slice_size + j) % len(cores)]
                        for j in range(slice_size)
                    ]
                    for i in range(n)
                ]
            else:
                core_sets = None  # everyone everywhere: full contention
            rows[mode][n] = launch(n, args.seconds, core_sets)
            print(f"{mode} x{n}: {rows[mode][n]:.4f}s", file=sys.stderr)

    header = "| mode | " + " | ".join(f"{n} pods" for n in pod_counts) + " |"
    sep = "|---" * (len(pod_counts) + 1) + "|"
    print(header)
    print(sep)
    for mode in rows:
        cells = " | ".join(f"{rows[mode][n]:.4f}" for n in pod_counts)
        print(f"| {mode} | {cells} |")


if __name__ == "__main__":
    main()
