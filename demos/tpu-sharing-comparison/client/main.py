"""Benchmark client: saturates a TPU slice/share with inference requests.

The TPU analogue of the reference's benchmarks client
(demos/gpu-sharing-comparison/client/main.py): a loop that constantly runs
inference on a small vision model and records per-inference latency. The
reference exports to Prometheus; here latencies stream to stdout as JSON
lines (one summary line every WINDOW seconds) so the harness — or a
PodMonitor sidecar — can scrape them.

Runs identically on a carved slice (google.com/tpu-slice-*), an HBM
fraction (google.com/tpu-mem-*gb), or a time-shared chip: the resource
request in the Pod manifest is the only difference, which is the point of
the comparison.
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time


def main() -> None:
    window = float(os.environ.get("REPORT_WINDOW_SECONDS", "10"))
    batch = int(os.environ.get("BATCH_SIZE", "8"))
    image = int(os.environ.get("IMAGE_SIZE", "224"))

    import jax
    import jax.numpy as jnp

    from nos_tpu.models.resnet import (
        init_resnet_params,
        resnet_forward,
        tiny_resnet_config,
    )

    config = tiny_resnet_config()
    params = init_resnet_params(jax.random.key(0), config)
    images = jnp.zeros((batch, image, image, 3), jnp.float32)
    infer = jax.jit(lambda p, x: resnet_forward(p, x, config))
    jax.block_until_ready(infer(params, images))  # compile outside the loop

    latencies: list = []
    window_start = time.monotonic()
    while True:
        start = time.monotonic()
        jax.block_until_ready(infer(params, images))
        latencies.append(time.monotonic() - start)
        now = time.monotonic()
        if now - window_start >= window:
            print(
                json.dumps(
                    {
                        "backend": jax.default_backend(),
                        "inferences": len(latencies),
                        "avg_s": statistics.fmean(latencies),
                        "p50_s": statistics.median(latencies),
                    }
                ),
                flush=True,
            )
            latencies.clear()
            window_start = now


if __name__ == "__main__":
    sys.exit(main())
