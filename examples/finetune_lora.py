"""LoRA fine-tune a Llama checkpoint, then serve the merged result.

The slice-tenant fine-tuning story end to end on whatever backend is
present (real chip or virtual CPU mesh):

  1. load / init a base model (optionally a HuggingFace checkpoint),
  2. train rank-r adapters with the frozen-base LoRA step,
  3. merge the delta into a dense checkpoint,
  4. quantize to int8 and generate from the artifact.

Run:  python examples/finetune_lora.py  [--real-weights /path/to/hf]
"""
import argparse

import os

# Platform decided BEFORE anything touches the default backend (an
# ambient TPU plugin would otherwise win — and hang if unreachable).
_PLATFORM = os.environ.get("NOS_EXAMPLE_PLATFORM", "cpu")

import jax

jax.config.update("jax_platforms", _PLATFORM)
import jax.numpy as jnp

from nos_tpu.models.generate import generate
from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.models.lora import (
    LoraConfig,
    init_lora_params,
    make_lora_train_step,
    merge_lora,
)
from nos_tpu.models.quantize import quantize_params
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.sharding import llama_param_sharding


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--real-weights", default="")
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--rank", type=int, default=8)
    args = parser.parse_args()

    if args.real_weights:
        from nos_tpu.models.convert import load_hf_llama

        params, config = load_hf_llama(args.real_weights)
    else:
        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)

    devices = jax.devices()
    shape = (max(1, len(devices) // 2), min(2, len(devices)))
    mesh = mesh_from_devices(shape, ("dp", "tp"), devices[: shape[0] * shape[1]])
    base = jax.device_put(params, llama_param_sharding(mesh, config))

    lora = LoraConfig(rank=args.rank)
    step, shard = make_lora_train_step(mesh, config, lora, learning_rate=3e-3)
    state = shard(init_lora_params(jax.random.key(1), config, lora))

    n_base = sum(x.size for x in jax.tree.leaves(params))
    n_lora = sum(x.size for x in jax.tree.leaves(state[0]))
    print(f"trainable: {n_lora:,} of {n_base:,} params "
          f"({100.0 * n_lora / n_base:.2f}%)")

    tokens = jax.random.randint(
        jax.random.key(2), (8, 32), 0, config.vocab_size
    )
    for i in range(args.steps):
        state, loss = step(state, base, tokens)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}")

    merged = merge_lora(jax.device_get(base), jax.device_get(state[0]), lora)
    artifact = quantize_params(merged)
    out = generate(
        artifact, jnp.asarray([[1, 2, 3, 4]], jnp.int32), config,
        max_new_tokens=12,
    )
    print("int8 serve of the fine-tuned artifact:", out[0].tolist())


if __name__ == "__main__":
    main()
