"""Preempt → checkpoint → resume, end to end.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/preempt_resume.py

The demo boots the full control-plane suite in-process (partitioner,
scheduler, operator, tpu agent, sim kubelet) over one v5e host, then plays
the elastic-quota story the framework exists for:

1. `trainer` (guaranteed 0 chips) borrows the whole 2x4 board and trains a
   tiny Llama with orbax checkpoints;
2. `claimant` (guaranteed the node) claims half — CapacityScheduling
   preempts the over-quota trainer, the freed board is re-carved;
3. the trainer resumes from its checkpoint on the remaining 2x2 slice —
   restored cross-mesh onto the smaller topology, training continues.
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

from nos_tpu.api.config import GpuPartitionerConfig, SchedulerConfig, TpuAgentConfig
from nos_tpu.api.v1alpha1 import constants, labels
from nos_tpu.api.v1alpha1.elasticquota import ElasticQuota, ElasticQuotaSpec
from nos_tpu.cmd import build_cluster
from nos_tpu.kube.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
)
from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.parallel.checkpoint import Checkpointer
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.train import make_train_step

CHIPS = constants.RESOURCE_TPU_CHIPS


def wait(pred, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def submit(store, name, ns, chips):
    store.create(
        Pod(
            metadata=ObjectMeta(name=name, namespace=ns),
            spec=PodSpec(
                containers=[Container(requests={constants.RESOURCE_TPU: chips})],
                scheduler_name=constants.SCHEDULER_NAME,
            ),
        )
    )


def phase(store, name, ns):
    pod = store.try_get("Pod", name, ns)
    return pod.status.phase if pod else "GONE"


def main() -> None:
    cluster = build_cluster(
        partitioner_config=GpuPartitionerConfig(
            batch_window_timeout_seconds=0.3, batch_window_idle_seconds=0.05
        ),
        scheduler_config=SchedulerConfig(retry_seconds=0.1),
    )
    alloc = {constants.RESOURCE_TPU: 8, "cpu": 64, "memory": 256}
    cluster.add_tpu_node(
        Node(
            metadata=ObjectMeta(
                name="tpu-0",
                labels={
                    labels.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
                    labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
                    labels.PARTITIONING_LABEL: "tpu",
                },
            ),
            status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
        ),
        agent_config=TpuAgentConfig(report_config_interval_seconds=0.1),
    )
    for ns, mn in (("trainer", 0), ("claimant", 8)):
        cluster.store.create(
            ElasticQuota(
                metadata=ObjectMeta(name=f"eq-{ns}", namespace=ns),
                spec=ElasticQuotaSpec(min={CHIPS: mn}, max={CHIPS: 8}),
            )
        )
    cluster.start()
    ckpt_dir = tempfile.mkdtemp(prefix="nos-tpu-demo-")
    try:
        # -------- phase 1: borrow the board, train, checkpoint
        submit(cluster.store, "train", "trainer", 8)
        assert wait(lambda: phase(cluster.store, "train", "trainer") == PodPhase.RUNNING)
        print("[1] trainer borrowed the full 2x4 board and is RUNNING")

        config = tiny_config()
        tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, config.vocab_size)
        mesh8 = mesh_from_devices((4, 2), ("dp", "tp"), jax.devices()[:8])
        step8, shard8 = make_train_step(mesh8, config)
        state = shard8(init_llama_params(jax.random.key(0), config), donate=True)
        with Checkpointer(ckpt_dir) as ckpt:
            for i in range(3):
                state, loss = step8(state, tokens)
                print(f"    step {i + 1}: loss {float(loss):.4f}  (8-chip mesh)")
            ckpt.save(3, state, force=True)
            ckpt.wait()
        print("[1] checkpoint saved at step 3")

        # -------- phase 2: the guaranteed owner claims; trainer preempted
        submit(cluster.store, "claim", "claimant", 4)
        assert wait(lambda: phase(cluster.store, "claim", "claimant") == PodPhase.RUNNING)
        assert wait(lambda: phase(cluster.store, "train", "trainer") != PodPhase.RUNNING)
        print("[2] claimant took its guaranteed 2x2; over-quota trainer preempted")

        # -------- phase 3: resume smaller, cross-mesh restore
        submit(cluster.store, "train-resume", "trainer", 4)
        assert wait(
            lambda: phase(cluster.store, "train-resume", "trainer") == PodPhase.RUNNING
        )
        print("[3] trainer rescheduled on the re-carved 2x2 slice")

        mesh4 = mesh_from_devices((2, 2), ("dp", "tp"), jax.devices()[:4])
        step4, shard4 = make_train_step(mesh4, config)
        like = shard4(init_llama_params(jax.random.key(7), config), donate=True)
        with Checkpointer(ckpt_dir) as ckpt:
            restored, step = ckpt.restore(like)
        for i in range(2):
            restored, loss = step4(restored, tokens)
            print(f"    step {step + i + 1}: loss {float(loss):.4f}  (4-chip mesh, resumed)")
        print("[3] training continued from the checkpoint on the smaller slice — done")
    finally:
        cluster.stop()


if __name__ == "__main__":
    main()
