"""End-to-end Llama training on whatever slice you were granted.

Run (CPU simulation of an 8-chip slice — the default):
    PYTHONPATH=. python examples/train_llama.py
Run on real chips:
    NOS_EXAMPLE_PLATFORM=tpu PYTHONPATH=. python examples/train_llama.py

On a real multi-host slice scheduled by nos-tpu, the same script runs
unchanged inside each gang member's container: ``distributed.initialize()``
picks up the expander-stamped coordinates (a no-op here), the mesh spans
every chip the control plane granted, and the pipeline feeds each data
shard directly.

The full workload stack in ~60 lines: deterministic input pipeline with
device prefetch, FSDP+tp sharding, optax AdamW with chip-fractional
optimizer state, per-layer remat + flash attention, and orbax
checkpointing that can resume on a DIFFERENT topology after preemption.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# NOS_EXAMPLE_PLATFORM=tpu runs on real chips; the default is the
# 8-device virtual CPU mesh, forced through the config API because an
# ambient JAX_PLATFORMS (e.g. a preinstalled TPU plugin) would otherwise
# win — and the platform must be decided BEFORE anything touches the
# default backend.
_PLATFORM = os.environ.get("NOS_EXAMPLE_PLATFORM", "cpu")
if _PLATFORM == "cpu" and "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np

from nos_tpu.parallel import distributed

distributed.initialize()  # no-op single-host; gang coordinates on a slice

import jax

jax.config.update("jax_platforms", _PLATFORM)
import optax

from nos_tpu.data import BatchLoader, prefetch_to_device
from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.parallel.checkpoint import Checkpointer
from nos_tpu.parallel.mesh import mesh_from_devices
from nos_tpu.parallel.sharding import llama_data_sharding
from nos_tpu.parallel.train import make_train_step

STEPS = 30
CHECKPOINT_EVERY = 10


def main() -> None:
    devices = jax.devices()
    # dp × tp over everything granted; flash+remat on real chips.
    on_tpu = _PLATFORM != "cpu"
    config = tiny_config(
        attention="flash" if on_tpu else "dense", remat=on_tpu
    )
    mesh = mesh_from_devices((len(devices) // 2, 2), ("dp", "tp"), devices)
    print(f"mesh: {dict(mesh.shape)} over {len(devices)} devices "
          f"({jax.device_count()} global)")

    optimizer = optax.chain(
        optax.clip_by_global_norm(1.0), optax.adamw(3e-3, weight_decay=0.01)
    )
    train_step, shard_state = make_train_step(mesh, config, optimizer=optimizer)
    state = shard_state(init_llama_params(jax.random.key(0), config), donate=True)

    corpus = np.random.default_rng(0).integers(
        0, config.vocab_size, size=1_000_000
    ).astype(np.int32)
    loader = BatchLoader(corpus, batch=16, seq_len=64, seed=0)
    ckpt_dir = tempfile.mkdtemp(prefix="nos-tpu-train-")
    with Checkpointer(ckpt_dir) as ckpt:
        start = ckpt.latest_step() or 0
        if start:
            state, start = ckpt.restore(state)
            loader.skip(start)
            print(f"resumed from step {start}")
        stream = prefetch_to_device(iter(loader), llama_data_sharding(mesh))
        for step, batch in zip(range(start + 1, STEPS + 1), stream):
            state, loss = train_step(state, batch)
            if step % 5 == 0:
                print(f"step {step:3d}  loss {float(loss):.4f}")
            if step % CHECKPOINT_EVERY == 0:
                ckpt.save(step, state, force=True)
        ckpt.wait()
    print(f"done; checkpoints in {ckpt_dir}")


if __name__ == "__main__":
    main()
