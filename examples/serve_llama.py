"""Serve a Llama checkpoint on a carved slice: the full serving stack.

Demonstrates the pieces working together on whatever backend is present
(real TPU chip, or the virtual CPU mesh for a dry run):

  1. int8 weight-only quantization (halved HBM; decode is
     weight-bandwidth-bound, so bytes read through to tokens/s),
  2. tensor-parallel sharding of the quantized weights over a mesh
     (Engine(mesh=...) + shard_for_serving: head-sharded KV cache),
  3. the continuous-batching Engine multiplexing mixed-length requests,
  4. speculative continuous batching (SpecEngine: a truncated draft
     verifies k tokens per target read),
  5. multi-tenant LoRA: co-tenant requests on DIFFERENT adapters over
     one shared base (per-row selector, S-LoRA style),
  6. one-off sampled generation with top-k / nucleus filtering.

Run:  python examples/serve_llama.py  [--real-weights /path/to/hf]
(NOS_EXAMPLE_PLATFORM=tpu for real chips; default is the CPU backend.)
With --real-weights, loads a HuggingFace Llama checkpoint via
nos_tpu.models.convert; otherwise serves a randomly initialized tiny
model (the mechanics, not the prose, are the demo).
"""
import argparse
import os
import time

# Platform decided BEFORE anything touches the default backend (an
# ambient TPU plugin would otherwise win — and hang if unreachable).
_PLATFORM = os.environ.get("NOS_EXAMPLE_PLATFORM", "cpu")

import jax

jax.config.update("jax_platforms", _PLATFORM)
import jax.numpy as jnp

from nos_tpu.models.generate import generate
from nos_tpu.models.llama import init_llama_params, tiny_config
from nos_tpu.models.quantize import quantize_params, weight_bytes
from nos_tpu.serve import Engine, GenRequest


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--real-weights", default="")
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--max-len", type=int, default=256)
    args = parser.parse_args()

    if args.real_weights:
        from nos_tpu.models.convert import load_hf_llama

        params, config = load_hf_llama(args.real_weights)
    else:
        config = tiny_config()
        params = init_llama_params(jax.random.key(0), config)

    dense_bytes = weight_bytes(params)
    params = quantize_params(params)
    print(
        f"int8 weights: {weight_bytes(params)/1e6:.1f} MB "
        f"({weight_bytes(params)/dense_bytes:.2f}x of bf16)"
    )

    mesh = None
    engine_params = params
    if len(jax.devices()) > 1 and config.n_kv_heads % 2 == 0:
        from nos_tpu.parallel.mesh import mesh_from_devices
        from nos_tpu.serve import shard_for_serving

        mesh = mesh_from_devices((2,), ("tp",), jax.devices()[:2])
        engine_params = shard_for_serving(params, mesh, config)
        print("tensor-parallel over 2 devices "
              "(Megatron params + head-sharded KV cache)")

    engine = Engine(
        engine_params, config, max_slots=args.slots, max_len=args.max_len,
        prefill_chunk=16, prefix_cache_entries=4, mesh=mesh,
    )
    rng = jax.random.key(0)
    # Requests share a "system prompt": with prefix caching on, only the
    # first admission prefills it — later ones hit the prefix LRU.
    rng, sub = jax.random.split(rng)
    system = jax.random.randint(sub, (40,), 1, config.vocab_size).tolist()
    ids = []
    for i in range(args.slots * 2):
        rng, sub = jax.random.split(rng)
        n = int(jax.random.randint(sub, (), 4, 24))
        prompt = system + jax.random.randint(sub, (n,), 1, config.vocab_size).tolist()
        ids.append(engine.submit(GenRequest(prompt=prompt, max_new_tokens=16)))
    start = time.monotonic()
    results = engine.run()
    wall = time.monotonic() - start
    total = sum(len(t) for t in results.values())
    from nos_tpu.util import metrics as m

    print(f"engine: {len(ids)} requests, {total} tokens in {wall:.2f}s "
          f"({total/wall:.1f} tok/s across {args.slots} slots, "
          f"{int(m.SERVE_PREFIX_HITS.value)} prefix-cache hits)")

    # Rolling sliding-window cache: a Mistral-style config serves a
    # stream far past the cache's physical length from O(window) HBM.
    if not args.real_weights:
        import dataclasses

        wcfg = dataclasses.replace(config, sliding_window=16)
        wparams = init_llama_params(jax.random.key(4), wcfg)
        roll = Engine(wparams, wcfg, max_slots=1, max_len=33,
                      ticks_per_sync=8, prefill_chunk=8, rolling=True)
        rid = roll.submit(GenRequest(prompt=[3, 1, 4, 1, 5] * 8,
                                     max_new_tokens=120))
        n = len(roll.run()[rid])
        print(f"rolling window: {40 + n} logical positions served through "
              f"a 33-slot cache (window 16)")

    # Speculative continuous batching: a 1-layer truncation of the
    # target drafts k tokens per round; acceptance is exact, so the
    # stats line is the whole story (a real deployment uses a distilled
    # draft checkpoint).
    if not args.real_weights:
        from nos_tpu.serve import SpecEngine

        draft_cfg = tiny_config(n_layers=1)
        draft = init_llama_params(jax.random.key(1), draft_cfg)
        spec = SpecEngine(
            params, config, draft, draft_cfg, k=4,
            max_slots=2, max_len=args.max_len,
        )
        for _ in range(4):
            rng, sub = jax.random.split(rng)
            prompt = jax.random.randint(sub, (12,), 1, config.vocab_size).tolist()
            spec.submit(GenRequest(prompt=prompt, max_new_tokens=16))
        spec.run()
        st = spec.stats()
        print(f"speculative engine: {st['rounds']} rounds, "
              f"mean accepted {st['mean_accepted']:.2f}/4 drafts per round")

    # Multi-tenant LoRA: two fine-tunes share the batch; each request
    # names its adapter (0 = bare base).
    if not args.real_weights:
        from nos_tpu.models.lora import (
            LoraConfig,
            init_lora_params,
            stack_lora_adapters,
        )

        lora_cfg = LoraConfig(rank=4)
        base = init_llama_params(jax.random.key(2), config)
        ads = [init_lora_params(jax.random.key(3 + i), config, lora_cfg)
               for i in range(2)]
        stacked = stack_lora_adapters(base, ads, lora_cfg, rows=2)
        ml = Engine(stacked, config, max_slots=2, max_len=64,
                    ticks_per_sync=4)
        ids = [ml.submit(GenRequest(prompt=[5, 9, 2], max_new_tokens=8,
                                    adapter=a)) for a in (0, 1, 2)]
        out = ml.run()
        print(f"multi-LoRA: {len(ids)} co-tenant requests over adapters "
              f"0/1/2 -> {[len(out[i]) for i in ids]} tokens each")

    sampled = generate(
        params,
        jnp.asarray([[1, 2, 3, 4]], jnp.int32),
        config,
        max_new_tokens=12,
        temperature=0.8,
        top_k=40,
        top_p=0.95,
        rng=jax.random.key(7),
    )
    print("sampled:", sampled[0].tolist())


if __name__ == "__main__":
    main()
