// tpuctl — host-local TPU slice control library.
//
// The native boundary of the suite, mirroring the role of the reference's
// CGO NVML client (pkg/gpu/nvml/client.go: the only code touching
// hardware). TPUs have no MIG-style hardware partitioner, so the concrete
// host-side artifact of a "slice" is (a) an entry in the host slice-state
// file the TPU device plugin re-exposes, and (b) a *chip assignment*: an
// ICI-contiguous rectangle of the board's chip grid. tpuctl owns both:
//
//  - atomic, flock-guarded read/modify/write of the per-node state file;
//  - a 2D/3D occupancy grid per board with first-fit rectangle placement
//    (any orientation), so fragmentation is tracked at chip granularity —
//    stricter than the control plane's multiset model, exactly like NVML
//    placement is stricter than MIG profile counts (the reference
//    brute-forces creation orders for the same reason,
//    pkg/gpu/nvml/client.go:286-340);
//  - device enumeration from /dev/accel* (overridable root for tests)
//    plus TPU runtime env (TPU_ACCELERATOR_TYPE / TPU_TOPOLOGY).
//
// State file format (line-based, versioned):
//   tpuctl/1
//   <device-id> <board> <profile> <chip,chip,...>
//
// All functions return 0 on success, negative on error, writing a message
// into err. Exposed with C linkage for ctypes.

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <string>
#include <sys/file.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

struct Topo {
  std::vector<int> dims;
  bool ok = false;
};

Topo parse_topo(const std::string& s) {
  Topo t;
  int value = 0;
  bool have = false;
  for (char c : s) {
    if (c >= '0' && c <= '9') {
      value = value * 10 + (c - '0');
      have = true;
    } else if (c == 'x' && have) {
      t.dims.push_back(value);
      value = 0;
      have = false;
    } else {
      return t;
    }
  }
  if (!have) return t;
  t.dims.push_back(value);
  for (int d : t.dims)
    if (d < 1) return t;
  t.ok = !t.dims.empty();
  return t;
}

int chips_of(const Topo& t) {
  int n = 1;
  for (int d : t.dims) n *= d;
  return n;
}

struct Slice {
  std::string id;
  int board;
  std::string profile;
  std::vector<int> chips;
};

struct State {
  std::vector<Slice> slices;
};

const char* kHeader = "tpuctl/1";

bool parse_state(FILE* f, State* out, std::string* err) {
  char line[4096];
  if (!fgets(line, sizeof line, f)) return true;  // empty file = empty state
  if (strncmp(line, kHeader, strlen(kHeader)) != 0) {
    *err = "bad state header";
    return false;
  }
  while (fgets(line, sizeof line, f)) {
    Slice s;
    char id[256], profile[64], chips[2048];
    int board;
    if (sscanf(line, "%255s %d %63s %2047s", id, &board, profile, chips) != 4) {
      continue;  // tolerate trailing newline/garbage
    }
    s.id = id;
    s.board = board;
    s.profile = profile;
    const char* p = chips;
    int v = 0;
    bool have = false;
    for (; *p; ++p) {
      if (*p >= '0' && *p <= '9') {
        v = v * 10 + (*p - '0');
        have = true;
      } else if (*p == ',' && have) {
        s.chips.push_back(v);
        v = 0;
        have = false;
      }
    }
    if (have) s.chips.push_back(v);
    out->slices.push_back(std::move(s));
  }
  return true;
}

bool write_state(const std::string& path, const State& state, std::string* err) {
  std::string tmp = path + ".tmp";
  FILE* f = fopen(tmp.c_str(), "w");
  if (!f) {
    *err = std::string("open tmp: ") + strerror(errno);
    return false;
  }
  fprintf(f, "%s\n", kHeader);
  for (const auto& s : state.slices) {
    fprintf(f, "%s %d %s ", s.id.c_str(), s.board, s.profile.c_str());
    for (size_t i = 0; i < s.chips.size(); ++i)
      fprintf(f, "%s%d", i ? "," : "", s.chips[i]);
    fprintf(f, "\n");
  }
  if (fflush(f) != 0 || fsync(fileno(f)) != 0) {
    *err = std::string("flush: ") + strerror(errno);
    fclose(f);
    return false;
  }
  fclose(f);
  if (rename(tmp.c_str(), path.c_str()) != 0) {
    *err = std::string("rename: ") + strerror(errno);
    return false;
  }
  return true;
}

// RAII flock on <path>.lock.
class FileLock {
 public:
  explicit FileLock(const std::string& path) {
    fd_ = open((path + ".lock").c_str(), O_CREAT | O_RDWR, 0644);
    if (fd_ >= 0) flock(fd_, LOCK_EX);
  }
  ~FileLock() {
    if (fd_ >= 0) {
      flock(fd_, LOCK_UN);
      close(fd_);
    }
  }
  bool held() const { return fd_ >= 0; }

 private:
  int fd_ = -1;
};

bool load_state(const std::string& path, State* state, std::string* err) {
  FILE* f = fopen(path.c_str(), "r");
  if (!f) {
    if (errno == ENOENT) return true;  // no file yet = empty state
    *err = std::string("open: ") + strerror(errno);
    return false;
  }
  bool ok = parse_state(f, state, err);
  fclose(f);
  return ok;
}

// Linear index of a coordinate in the board grid (row-major).
int grid_index(const std::vector<int>& board, const std::vector<int>& coord) {
  int idx = 0;
  for (size_t i = 0; i < board.size(); ++i) idx = idx * board[i] + coord[i];
  return idx;
}

// Backtracking placement of a set of slices onto the occupancy grid.
// Largest-first ordering is the good heuristic start; full backtracking
// makes placement order-independent — the problem the reference works
// around by brute-forcing NVML creation-order permutations
// (pkg/gpu/nvml/client.go:286-340) is solved exactly here.
bool place_all(const Topo& board, std::vector<bool>& occupied,
               const std::vector<Topo>& profiles, size_t index,
               std::vector<std::vector<int>>* out) {
  if (index == profiles.size()) return true;
  const Topo& prof = profiles[index];
  std::vector<int> dims = prof.dims;
  std::sort(dims.begin(), dims.end());
  std::vector<std::vector<int>> orients;
  do {
    if (dims.size() == board.dims.size()) orients.push_back(dims);
  } while (std::next_permutation(dims.begin(), dims.end()));

  int rank = (int)board.dims.size();
  std::vector<int> anchor(rank, 0);
  for (;;) {
    for (const auto& o : orients) {
      bool fits = true;
      for (int i = 0; i < rank && fits; ++i)
        if (anchor[i] + o[i] > board.dims[i]) fits = false;
      if (!fits) continue;
      // Collect the covered cells; check all free.
      std::vector<int> cells;
      std::vector<int> offset(rank, 0);
      bool free_all = true;
      for (;;) {
        std::vector<int> coord(rank);
        for (int i = 0; i < rank; ++i) coord[i] = anchor[i] + offset[i];
        int idx = grid_index(board.dims, coord);
        if (occupied[idx]) {
          free_all = false;
          break;
        }
        cells.push_back(idx);
        int axis = rank - 1;
        while (axis >= 0) {
          if (++offset[axis] < o[axis]) break;
          offset[axis] = 0;
          --axis;
        }
        if (axis < 0) break;
      }
      if (!free_all) continue;
      for (int c : cells) occupied[c] = true;
      (*out)[index] = cells;
      if (place_all(board, occupied, profiles, index + 1, out)) return true;
      for (int c : cells) occupied[c] = false;
    }
    int axis = rank - 1;
    while (axis >= 0) {
      if (++anchor[axis] < board.dims[axis]) break;
      anchor[axis] = 0;
      --axis;
    }
    if (axis < 0) break;
  }
  return false;
}

int fail(char* err, int errcap, const std::string& message, int code = -1) {
  if (err && errcap > 0) snprintf(err, errcap, "%s", message.c_str());
  return code;
}

int emit(char* out, int cap, const std::string& s) {
  if ((int)s.size() + 1 > cap) return -2;  // caller buffer too small
  memcpy(out, s.c_str(), s.size() + 1);
  return (int)s.size();
}

}  // namespace

extern "C" {

// Enumerate accelerator device nodes under dev_root (e.g. "/dev"): counts
// files named accel* (TPU chips appear as /dev/accel0..N or vfio entries).
// Output: "<count>\n<name>\n<name>...". Env TPU_ACCELERATOR_TYPE /
// TPU_TOPOLOGY are appended as "env <k>=<v>" lines when present.
int tpuctl_enumerate(const char* dev_root, char* out, int cap) {
  std::string result;
  int count = 0;
  std::string names;
  std::string root = dev_root ? dev_root : "/dev";
  DIR* d = opendir(root.c_str());
  if (d) {
    while (dirent* e = readdir(d)) {
      if (strncmp(e->d_name, "accel", 5) != 0 &&
          strncmp(e->d_name, "vfio", 4) != 0)
        continue;
      // Skip directories (e.g. the /dev/vfio container dir itself); only
      // device nodes / files count as accelerators.
      struct stat st;
      std::string path = root + "/" + e->d_name;
      if (stat(path.c_str(), &st) == 0 && S_ISDIR(st.st_mode)) continue;
      ++count;
      names += e->d_name;
      names += "\n";
    }
    closedir(d);
  }
  char buf[64];
  snprintf(buf, sizeof buf, "%d\n", count);
  result = buf + names;
  for (const char* key : {"TPU_ACCELERATOR_TYPE", "TPU_TOPOLOGY"}) {
    const char* value = getenv(key);
    if (value) {
      result += "env ";
      result += key;
      result += "=";
      result += value;
      result += "\n";
    }
  }
  return emit(out, cap, result);
}

// List slices: one "<id> <board> <profile> <chips>" line per slice.
int tpuctl_list_slices(const char* state_path, char* out, int cap, char* err,
                       int errcap) {
  FileLock lock(state_path);
  if (!lock.held()) return fail(err, errcap, "cannot acquire lock");
  State state;
  std::string e;
  if (!load_state(state_path, &state, &e)) return fail(err, errcap, e);
  std::string result;
  for (const auto& s : state.slices) {
    result += s.id + " " + std::to_string(s.board) + " " + s.profile + " ";
    for (size_t i = 0; i < s.chips.size(); ++i)
      result += (i ? "," : "") + std::to_string(s.chips[i]);
    result += "\n";
  }
  return emit(out, cap, result);
}

// Create a batch of slices ("profile:qty,profile:qty") on one board,
// assigning ICI-contiguous chips with backtracking so the outcome does not
// depend on creation order. All-or-nothing.
int tpuctl_create_slices_batch(const char* state_path,
                               const char* board_topology, int board_index,
                               const char* spec, char* err, int errcap) {
  Topo board = parse_topo(board_topology ? board_topology : "");
  if (!board.ok) return fail(err, errcap, "invalid board topology");

  std::vector<std::pair<Topo, std::string>> wanted;  // (topo, name)
  {
    std::string s = spec ? spec : "";
    size_t pos = 0;
    while (pos < s.size()) {
      size_t comma = s.find(',', pos);
      size_t end = comma == std::string::npos ? s.size() : comma;
      std::string item = s.substr(pos, end - pos);
      size_t colon = item.find(':');
      if (colon == std::string::npos)
        return fail(err, errcap, "bad spec item: " + item);
      std::string name = item.substr(0, colon);
      int qty = atoi(item.c_str() + colon + 1);
      Topo t = parse_topo(name);
      if (!t.ok || t.dims.size() != board.dims.size())
        return fail(err, errcap, "invalid profile topology: " + name);
      if (qty < 1) return fail(err, errcap, "quantity must be >= 1");
      for (int i = 0; i < qty; ++i) wanted.push_back({t, name});
      if (comma == std::string::npos) break;
      pos = comma + 1;
    }
  }
  if (wanted.empty()) return 0;
  // Largest-first: best heuristic order for the backtracking search.
  std::stable_sort(wanted.begin(), wanted.end(),
                   [](const auto& a, const auto& b) {
                     return chips_of(a.first) > chips_of(b.first);
                   });

  FileLock lock(state_path);
  if (!lock.held()) return fail(err, errcap, "cannot acquire lock");
  State state;
  std::string e;
  if (!load_state(state_path, &state, &e)) return fail(err, errcap, e);

  std::vector<bool> occupied(chips_of(board), false);
  int max_id = 0;
  for (const auto& s : state.slices) {
    if (s.board == board_index)
      for (int c : s.chips)
        if (c >= 0 && c < (int)occupied.size()) occupied[c] = true;
    size_t dash = s.id.rfind('-');
    if (dash != std::string::npos)
      max_id = std::max(max_id, atoi(s.id.c_str() + dash + 1));
  }

  std::vector<Topo> profiles;
  for (const auto& w : wanted) profiles.push_back(w.first);
  std::vector<std::vector<int>> positions(profiles.size());
  if (!place_all(board, occupied, profiles, 0, &positions))
    return fail(err, errcap,
                std::string("no contiguous placement for batch ") + spec +
                    " (fragmented board)",
                -3);
  for (size_t i = 0; i < wanted.size(); ++i) {
    Slice s;
    s.board = board_index;
    s.profile = wanted[i].second;
    s.chips = positions[i];
    s.id = std::string("tpu-") + std::to_string(board_index) + "-" +
           wanted[i].second + "-" + std::to_string(++max_id);
    state.slices.push_back(std::move(s));
  }
  if (!write_state(state_path, state, &e)) return fail(err, errcap, e);
  return 0;
}

// Single-profile convenience wrapper.
int tpuctl_create_slices(const char* state_path, const char* board_topology,
                         int board_index, const char* profile, int quantity,
                         char* err, int errcap) {
  if (!profile || quantity < 1)
    return fail(err, errcap, "quantity must be >= 1");
  std::string spec = std::string(profile) + ":" + std::to_string(quantity);
  return tpuctl_create_slices_batch(state_path, board_topology, board_index,
                                    spec.c_str(), err, errcap);
}

int tpuctl_delete_slice(const char* state_path, const char* device_id,
                        char* err, int errcap) {
  FileLock lock(state_path);
  if (!lock.held()) return fail(err, errcap, "cannot acquire lock");
  State state;
  std::string e;
  if (!load_state(state_path, &state, &e)) return fail(err, errcap, e);
  size_t before = state.slices.size();
  state.slices.erase(
      std::remove_if(state.slices.begin(), state.slices.end(),
                     [&](const Slice& s) { return s.id == device_id; }),
      state.slices.end());
  if (state.slices.size() == before)
    return fail(err, errcap, std::string("slice not found: ") + device_id, -4);
  if (!write_state(state_path, state, &e)) return fail(err, errcap, e);
  return 0;
}

// Delete every slice except the ids in keep (comma-separated) — startup
// cleanup of orphans (reference cmd/migagent/migagent.go:190-199).
int tpuctl_delete_all_except(const char* state_path, const char* keep,
                             char* err, int errcap) {
  FileLock lock(state_path);
  if (!lock.held()) return fail(err, errcap, "cannot acquire lock");
  State state;
  std::string e;
  if (!load_state(state_path, &state, &e)) return fail(err, errcap, e);
  std::string keep_s = keep ? keep : "";
  auto kept = [&](const std::string& id) {
    size_t pos = 0;
    while (pos <= keep_s.size()) {
      size_t comma = keep_s.find(',', pos);
      size_t end = comma == std::string::npos ? keep_s.size() : comma;
      if (keep_s.compare(pos, end - pos, id) == 0 && end - pos == id.size())
        return true;
      if (comma == std::string::npos) break;
      pos = comma + 1;
    }
    return false;
  };
  state.slices.erase(
      std::remove_if(state.slices.begin(), state.slices.end(),
                     [&](const Slice& s) { return !kept(s.id); }),
      state.slices.end());
  if (!write_state(state_path, state, &e)) return fail(err, errcap, e);
  return 0;
}

}  // extern "C"
