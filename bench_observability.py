"""Observability-plane benchmark: the telemetry stack at fleet scale.

PR 18 proved the store and planning planes at 100k nodes / 1M pods; this
bench proves the *observability* plane survives the same world. It rides
``bench_store``'s builders (nodes, round-robin bound pods, a pending
residue), derives the fleet's per-node capacity series from the seeded
store, and measures the pieces the control loops actually pay for:

  exposition      — ``MetricsRegistry.render()`` with the cardinality
                    governor OFF (the ~3-series-per-node floor) and ON
                    (budgeted exact series + the ``_other`` fold)
  snapshot        — ``SnapshotCursor.collect()`` after touching a quiet
                    interval's worth of series (O(changed), not O(total))
  timeline sample — ``TimelineStore.sample_once()`` in registry-cursor
                    mode over the governed registry
  retention       — a deterministic journey mixture (boring / slow /
                    error) through the tail-kept ``TraceStore``

Wall-clock numbers go to stdout only. The committed report
(``BENCH_observability.json``) is bit-stable: series counts, exposition
byte sizes and the governed exposition's sha256, the governor on/off A/B
deltas, trace retention hit-rate, and ``*_within_budget`` booleans
holding each governed-path cost to <=2% of the 5s control cycle (the
PR 9 overhead budget). Two independently built governed registries must
render byte-identically — the governor is a deterministic function of
the series set, and the determinism tests pin it.

  make bench-obs
  python bench_observability.py --quick
  python bench_observability.py --output BENCH_observability.json
"""
from __future__ import annotations

import argparse
import hashlib
import json
import statistics
import time

from bench_store import seed_store
from nos_tpu.api.v1alpha1 import constants
from nos_tpu.timeline.sizes import SizeRegistry
from nos_tpu.timeline.store import TimelineStore
from nos_tpu.timeline.watchdog import WedgeWatchdog
from nos_tpu.util.metrics import MetricsRegistry
from nos_tpu.util.tracing import RetentionPolicy, Span, Trace, TraceStore

CYCLE_SECONDS = 5.0
BUDGET_FRACTION = 0.02  # each governed-path cost <= 2% of the cycle
NODE_FAMILY = "nos_tpu_capacity_node_chips"
POOL_FAMILY = "nos_tpu_capacity_pool_chips"
NODE_STATES = ("used", "free", "stranded")
N_POOLS = 8
NODE_BUDGET = 4096  # exact per-node series the governor admits
TOUCHED_PER_FRAME = 256  # a quiet interval's changed-series count
SLOW_THRESHOLDS = {"pod.journey": 1.0}


def fleet_from_store(store):
    """Deterministic (node, capacity, used_chips) rows + the pending-pod
    count, derived from the seeded store (each bound pod requests 1 chip,
    exactly as bench_store builds them)."""
    used: dict = {}
    pending = 0
    for pod in store.list("Pod", copy=False):
        node = pod.spec.node_name
        if node:
            used[node] = used.get(node, 0) + 1
        else:
            pending += 1
    fleet = []
    for node in store.list("Node", copy=False):
        cap = int(node.status.allocatable.get(constants.RESOURCE_TPU, 0))
        fleet.append((node.metadata.name, cap, used.get(node.metadata.name, 0)))
    return fleet, pending


def emit_fleet(registry, fleet, pending):
    """Publish the fleet as the ledger would: one ``{node,state}`` series
    triple per node (the cardinality the governor must bound) plus exact
    per-pool rollups and the pending-pods gauge."""
    node_g = registry.gauge(NODE_FAMILY, "per-node chip accounting")
    pool_g = registry.gauge(POOL_FAMILY, "per-pool chip rollups")
    pending_g = registry.gauge("nos_tpu_capacity_pending_pods", "unbound pods")
    pools: dict = {}
    for i, (name, cap, used_chips) in enumerate(fleet):
        free = cap - used_chips
        stranded = 1 if 0 < used_chips < cap else 0
        node_g.labels(node=name, state="used").set(float(used_chips))
        node_g.labels(node=name, state="free").set(float(free))
        node_g.labels(node=name, state="stranded").set(float(stranded))
        acc = pools.setdefault(f"pool-{i % N_POOLS}", [0, 0, 0])
        acc[0] += used_chips
        acc[1] += free
        acc[2] += stranded
    for pool in sorted(pools):
        for state, value in zip(NODE_STATES, pools[pool]):
            pool_g.labels(pool=pool, state=state).set(float(value))
    pending_g.set(float(pending))
    return node_g


def governed_registry(fleet, pending):
    registry = MetricsRegistry()
    registry.apply_series_budgets({NODE_FAMILY: NODE_BUDGET})
    emit_fleet(registry, fleet, pending)
    return registry


def make_trace(trace_id, duration, status="ok"):
    root = Span(
        name="pod.journey",
        trace_id=trace_id,
        span_id=f"{trace_id}-root",
        parent_id=None,
        duration_s=duration,
        status=status,
    )
    return Trace(trace_id=trace_id, spans=[root])


def drive_retention(n_traces):
    """Deterministic journey mixture: mostly boring, every 53rd slow,
    every 101st an error — the burst shape that evicted the interesting
    tail out of the newest-kept store."""
    store = TraceStore(
        capacity=256,
        retention=RetentionPolicy(
            tail_capacity=64, boring_sample_n=8, slow_thresholds=SLOW_THRESHOLDS
        ),
    )
    for i in range(n_traces):
        if i % 101 == 0:
            store.add(make_trace(f"t{i:06d}", 0.1, status="error"))
        elif i % 53 == 0:
            store.add(make_trace(f"t{i:06d}", 2.0))
        else:
            store.add(make_trace(f"t{i:06d}", 0.1))
    return store.retention_stats()


def _p50_ms(fn, repeats):
    durations = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        durations.append(time.perf_counter() - t0)
    return round(statistics.median(durations) * 1e3, 3)


def _touch(node_gauge, fleet, frame):
    """Nudge a rotating window of node series — the quiet-interval write
    pattern the cursor pays for."""
    n = min(TOUCHED_PER_FRAME, len(fleet))
    for j in range(n):
        name, cap, used_chips = fleet[(frame * n + j) % len(fleet)]
        node_gauge.labels(node=name, state="used").set(float(used_chips + frame))


def run_config(n_nodes, n_pods, repeats):
    limit_ms = CYCLE_SECONDS * BUDGET_FRACTION * 1e3
    t0 = time.perf_counter()
    store = seed_store(n_nodes, n_pods)
    fleet, pending = fleet_from_store(store)
    seed_s = time.perf_counter() - t0
    del store

    # Governor OFF: the floor the fleet would pay without budgets.
    ungoverned = MetricsRegistry()
    emit_fleet(ungoverned, fleet, pending)
    ungoverned_active = sum(
        fam["exact"] + fam["overflow"] for fam in ungoverned.series_report().values()
    )
    ungoverned_render = ungoverned.render()
    off_p50 = _p50_ms(ungoverned.render, repeats)
    del ungoverned

    # Governor ON, built twice from scratch: the exposition must be a
    # deterministic function of the series set (byte-identical renders).
    governed = governed_registry(fleet, pending)
    twin_render = governed_registry(fleet, pending).render()
    governed_render = governed.render()
    report = governed.series_report()
    node_fam = report[NODE_FAMILY]
    governed_active = sum(f["exact"] + f["overflow"] for f in report.values())
    on_p50 = _p50_ms(governed.render, repeats)

    # Incremental snapshot + timeline sample over the governed registry.
    cursor = governed.cursor()
    cursor.collect()  # prime: full snapshot
    node_gauge = governed.gauge(NODE_FAMILY)
    snap_durations = []
    for frame in range(repeats):
        _touch(node_gauge, fleet, frame)
        t1 = time.perf_counter()
        changed, _ = cursor.collect()
        snap_durations.append(time.perf_counter() - t1)
    snapshot_p50 = round(statistics.median(snap_durations) * 1e3, 3)
    snapshot_changed = len(changed)
    cursor.close()

    virtual_now = [1000.0]

    def clock():
        virtual_now[0] += CYCLE_SECONDS
        return virtual_now[0]

    timeline = TimelineStore(
        clock=clock,
        vitals=False,
        registry=governed,
        sizes=SizeRegistry(),
        watchdog=WedgeWatchdog(),
    )
    timeline.sample_once()  # prime the cursor
    sample_durations = []
    for frame in range(repeats):
        _touch(node_gauge, fleet, frame + repeats)
        t1 = time.perf_counter()
        timeline.sample_once()
        sample_durations.append(time.perf_counter() - t1)
    sample_p50 = round(statistics.median(sample_durations) * 1e3, 3)
    timeline.close()

    retention = drive_retention(max(202, min(10_000, n_pods // 100)))

    timing = {
        "bench": "bench_observability_timing",
        "nodes": n_nodes,
        "pods": n_pods,
        "seed_seconds": round(seed_s, 2),
        "exposition_off_p50_ms": off_p50,
        "exposition_on_p50_ms": on_p50,
        "snapshot_p50_ms": snapshot_p50,
        "timeline_sample_p50_ms": sample_p50,
        "limit_ms": limit_ms,
    }
    row = {
        "bench": "bench_observability",
        "nodes": n_nodes,
        "pods": n_pods,
        "series": {
            "ungoverned_active": ungoverned_active,
            "governed_active": governed_active,
            "governed_exact": node_fam["exact"],
            "governed_overflow": node_fam["overflow"],
            "dropped": node_fam["dropped"],
            "node_family_budget": NODE_BUDGET,
        },
        "exposition": {
            "bytes_ungoverned": len(ungoverned_render),
            "bytes_governed": len(governed_render),
            "governed_sha256": hashlib.sha256(
                governed_render.encode()
            ).hexdigest(),
            "byte_identical": governed_render == twin_render,
        },
        "snapshot": {
            "changed_series_per_frame": snapshot_changed,
            "primed_series": governed_active,
        },
        "retention": {
            "traces": sum(retention["seen"].values()),
            "seen": retention["seen"],
            "kept": retention["kept"],
            "sampled_out": retention["sampled_out"],
            "hit_rate": retention["hit_rate"],
        },
        "overhead": {
            "cycle_seconds": CYCLE_SECONDS,
            "budget_fraction": BUDGET_FRACTION,
            "exposition_within_budget": on_p50 <= limit_ms,
            "snapshot_within_budget": snapshot_p50 <= limit_ms,
            "timeline_sample_within_budget": sample_p50 <= limit_ms,
        },
    }
    return row, timing


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--configs",
        default="1000x10000,100000x1000000",
        help="comma-separated nodesxpods pairs",
    )
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument(
        "--quick", action="store_true", help="100x1000 only, fewer repeats"
    )
    parser.add_argument("--output", default="", help="write the report JSON here")
    args = parser.parse_args()

    configs = [tuple(map(int, c.split("x"))) for c in args.configs.split(",")]
    repeats = args.repeats
    if args.quick:
        configs = [(100, 1000)]
        repeats = 2

    rows = []
    for n_nodes, n_pods in configs:
        row, timing = run_config(n_nodes, n_pods, repeats)
        rows.append(row)
        print(json.dumps(timing), flush=True)
        print(json.dumps(row), flush=True)

    report = {
        "budget": {
            "cycle_seconds": CYCLE_SECONDS,
            "overhead_fraction": BUDGET_FRACTION,
        },
        "rows": rows,
    }
    if args.output:
        with open(args.output, "w") as fh:
            fh.write(json.dumps(report, indent=1, sort_keys=True) + "\n")


if __name__ == "__main__":
    main()
