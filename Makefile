# nos-tpu build/test entry points (reference Makefile analogue).

PY ?= python
IMAGE_REGISTRY ?= ghcr.io/nos-tpu
VERSION ?= 0.1.0
COMPONENTS := operator partitioner scheduler tpuagent sharingagent metricsexporter

.PHONY: all test test-fast test-unit test-integration replay-smoke chaos-smoke chaos capacity-smoke serve-smoke autoscale-smoke shard-smoke procpool-smoke forecast-smoke soak-smoke obs-smoke incluster-e2e kind-e2e bench bench-planner bench-store bench-serve bench-autoscale bench-forecast bench-soak bench-obs bench-trend examples native lint \
        docker-build $(addprefix docker-build-,$(COMPONENTS)) \
        helm-lint deploy undeploy clean

all: native test

## Tests -----------------------------------------------------------------

test:
	$(PY) -m pytest tests/ -q

# Fast tier: the control plane (seconds per dir). The ML/JAX tier
# (tests/models tests/ops tests/parallel) compiles real programs and runs
# in CI's nightly job instead.
test-fast:
	$(PY) -m pytest tests/api tests/cmd tests/controllers tests/device \
	    tests/kube tests/partitioning tests/scheduler tests/tpu tests/util \
	    tests/integration tests/data -q

test-unit:
	$(PY) -m pytest tests/ -q --ignore=tests/integration

test-integration:
	$(PY) -m pytest tests/integration -q

# Flight-recorder loop: record a short sim run via the `run` CLI, replay
# it via the `replay` CLI, and require zero decision drift and zero audit
# violations. Non-slow — tier-1 exercises the full loop.
replay-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/record/test_replay_smoke.py -q

# Capacity-ledger gate: incremental chip-seconds accounting agrees with a
# from-scratch shadow recompute, /debug/capacity serves the rollups, and
# recorded observes replay with zero drift.
capacity-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/capacity -q -m 'not slow'

# Serving-SLO gate: seed-pinned open-loop driver run on the tiny CPU
# model — deterministic BENCH_serve.json shape, SLO verdicts stable
# across two runs, TTFT stamping and burn-rate math vs fixtures.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/slo -q -m 'not slow'

# Autoscaler gate: the ModelServing policy/reconciler unit tier plus a
# short seeded closed loop (workload -> burn rate -> replica pods ->
# carve) that must be byte-identical across two in-process runs.
autoscale-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/controllers/test_autoscaler.py \
	    tests/slo/test_autoscale_smoke.py -q -m 'not slow'

# Pool-sharded planning gate: pool partitioning + merge invariants,
# warm-state codec round-trip/versioning, the sharded controller path,
# and a tiny end-to-end sharded bench run (cold + replans + merge +
# equivalence + warm boot on a 64-node / 2-pool world).
shard-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/partitioning/test_pools.py \
	    tests/partitioning/test_snapcodec.py \
	    tests/controllers/test_sharded_controller.py -q -m 'not slow'
	JAX_PLATFORMS=cpu $(PY) bench_planner.py --plan-mode sharded --quick

# Multi-process pool planning gate: wire framing + warm-state transport
# through real spawned workers, the process-spawner watchdog lint, and
# the end-to-end A/B — a 2-pool process-backend controller byte-identical
# to its serial twin, including a worker killed mid-stream recovering
# with zero drift.
procpool-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/partitioning/test_procpool.py \
	    tests/controllers/test_procpool_smoke.py \
	    tests/timeline/test_thread_lint.py -q -m 'not slow'

# Placement-forecaster gate: engine/advisor/accuracy unit tier plus the
# streaming calibration bench run twice in-process — byte-identical
# reports at the pinned seed and the accuracy auditor clean on replay.
forecast-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/forecast -q -m 'not slow'

# Health-timeline gate: detector/store/watchdog unit tier, the teeth
# tests (deliberate leak/stall/regression each producing an Event plus a
# bit-exact replayable flight record), and a seconds-long 64-node soak
# whose verdicts must be byte-identical across two in-process runs.
soak-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/timeline -q -m 'not slow'

# Observability-plane gate: cardinality governor admission/fold/budget
# semantics, incremental snapshot cursors, tail-kept trace retention,
# streaming debug pagination, and the small-world end-to-end smoke —
# two in-process runs of the governed plane must be byte-identical.
obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/obsplane -q -m 'not slow'

# Chaos tier-1 gate: one fixed seed through the full suite under fault
# injection — must converge, replay clean, and fire a byte-identical
# fault schedule every run. Plus the committed regression fixtures.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/chaos -q -m 'not slow'
	JAX_PLATFORMS=cpu $(PY) -m nos_tpu chaos --seed 7 --bursts 2 --nodes 2 \
	    --burst-seconds 0.4 --timeout 30 --backend memory

# Slow soak: many seeds on both backends (see tests/chaos/test_sweep.py),
# then a wide memory sweep via the CLI. Each seed must converge with zero
# oracle violations and replay with zero drift.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/chaos -q
	JAX_PLATFORMS=cpu $(PY) -m nos_tpu chaos --seed 0 --sweep 50 --bursts 2 \
	    --burst-seconds 0.4 --timeout 30 --backend memory --no-minimize

# Hardware-free in-cluster dry run: real component processes against the
# sim apiserver over HTTP (see hack/kind/README.md for the real-kind tier).
incluster-e2e:
	PYTHONPATH=. $(PY) hack/incluster_e2e.py

kind-e2e:
	kind create cluster --name nos-tpu --config hack/kind/cluster.yaml
	helm install nos-tpu helm-charts/nos-tpu -f hack/kind/values.yaml
	kubectl apply -f hack/kind/smoke-pod.yaml
	kubectl wait pod/tpu-smoke --for=jsonpath='{.spec.nodeName}' --timeout=120s

bench:
	$(PY) bench.py

# Partitioner plan() latency: CoW snapshot engine vs the deepcopy
# baseline, synthetic clusters, CPU-only. Appends JSON lines with
# --output; see BENCH_planner.json for the committed numbers.
bench-planner:
	JAX_PLATFORMS=cpu $(PY) bench_planner.py

# Shared-store verb throughput (list, list_by_index indexed vs scan,
# patch, watch fanout, apply_event) at 1k×10k and 10k×100k scale. See
# BENCH_store.json for the committed numbers.
bench-store:
	JAX_PLATFORMS=cpu $(PY) bench_store.py --output BENCH_store.json

# Open-loop serving workload (seeded Poisson arrivals, hot/cold model
# skew, diurnal shaping) against the continuous-batching engine on a
# virtual cost-model clock: TTFT/TPOT/e2e percentiles, goodput, and SLO
# verdicts, bit-stable at the pinned seed. See BENCH_serve.json.
bench-serve:
	JAX_PLATFORMS=cpu $(PY) bench_serve.py --output BENCH_serve.json

# The serving autoscaler's closed loop on a live SimCluster: diurnal
# workload -> SLO burn -> ModelServing verdicts -> replica pods ->
# gang-place + carve, with scale-to-zero chip reclamation accounted by a
# shadow capacity ledger. Bit-stable at the pinned seed. See
# BENCH_autoscale.json.
bench-autoscale:
	JAX_PLATFORMS=cpu $(PY) bench_autoscale.py --output BENCH_autoscale.json

# Placement-forecaster calibration on a streaming BENCH_r05-style
# workload over a virtual clock: per-gang ETA stamps joined against
# observed binds through the real capacity-ledger listener, defrag
# advisor validation, and a zero-drift replay of the forecast records.
# Bit-stable at the pinned seed. See BENCH_forecast.json.
bench-forecast:
	JAX_PLATFORMS=cpu $(PY) bench_forecast.py --output BENCH_forecast.json

# Longitudinal health soak: 220 pool-sharded plan cycles at 1024 nodes
# with the forecaster, the autoscaler decision loop, and the timeline
# sampler interleaved A/B on a virtual clock. Zero leak/stall findings,
# sampling overhead within budget, zero replay drift; bit-stable at the
# pinned seed. See BENCH_soak.json.
bench-soak:
	JAX_PLATFORMS=cpu $(PY) bench_soak.py --output BENCH_soak.json

# Observability plane at fleet cardinality: governor on/off exposition
# A/B, incremental snapshot + timeline sample costs, and trace retention
# over bench_store's 100k-node / 1M-pod world. Wall-clock goes to
# stdout; the committed report keeps deterministic counts, shas, and
# within-budget booleans only. See BENCH_observability.json.
bench-obs:
	JAX_PLATFORMS=cpu $(PY) bench_observability.py --output BENCH_observability.json

# Committed-benchmark trend gate: diff every BENCH_*.json in the working
# tree against the previous commit's copy and flag regressions past the
# per-metric tolerance. Read-only — exits nonzero only on malformed
# inputs, so CI logs the trend without failing on noisy perf numbers.
bench-trend:
	$(PY) tools/bench_trend.py

## Examples (CPU-simulated slices by default; NOS_EXAMPLE_PLATFORM=tpu
## for real chips) -------------------------------------------------------

examples:
	PYTHONPATH=. $(PY) examples/train_llama.py
	PYTHONPATH=. $(PY) examples/preempt_resume.py

## Native ----------------------------------------------------------------

native:
	$(MAKE) -C native

## Lint ------------------------------------------------------------------

lint:
	$(PY) -m compileall -q nos_tpu tests bench.py __graft_entry__.py
	$(PY) tools/lint.py
	$(PY) -c "import yaml,glob; [list(yaml.safe_load_all(open(f).read())) for f in glob.glob('config/**/*.yaml', recursive=True)]; print('config/ yaml ok')"

## Images ----------------------------------------------------------------

docker-build: $(addprefix docker-build-,$(COMPONENTS))

docker-build-%:
	docker build -f build/$*/Dockerfile -t $(IMAGE_REGISTRY)/nos-tpu-$*:$(VERSION) .

## Deploy ----------------------------------------------------------------

helm-lint:
	helm lint helm-charts/nos-tpu

deploy:
	kubectl apply -k config/default

undeploy:
	kubectl delete -k config/default

clean:
	rm -rf native/build native/libtpuctl.so .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
