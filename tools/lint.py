"""AST-based linter for nos_tpu (ruff/pyflakes are not in this image).

Checks, per file:
  F401  unused import              (skipped in __init__.py re-export surfaces)
  F811  redefinition in same scope (function/class defined twice)
  F841  unused local variable      (assigned once, never read, not _-prefixed)
  B006  mutable default argument   (list/dict/set literal or call)
  E722  bare except
  F541  f-string without placeholders
  T100  TODO/FIXME/XXX marker

Usage: python tools/lint.py [paths...]   (default: nos_tpu tests examples
bench.py __graft_entry__.py). Exits 1 if any finding. A `# noqa` on the
offending line suppresses it; `# noqa: F401` suppresses one code.
"""
from __future__ import annotations

import ast
import os
import re
import sys

DEFAULT_TARGETS = ["nos_tpu", "tests", "examples", "bench.py", "__graft_entry__.py"]
MARKER_RE = re.compile(r"\b(TODO|FIXME|XXX)\b")
NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?", re.IGNORECASE)


class Finding:
    def __init__(self, path: str, line: int, code: str, msg: str) -> None:
        self.path, self.line, self.code, self.msg = path, line, code, msg

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.msg}"


def _suppressed(source_lines: list, finding: Finding) -> bool:
    if not (1 <= finding.line <= len(source_lines)):
        return False
    m = NOQA_RE.search(source_lines[finding.line - 1])
    if not m:
        return False
    codes = m.group("codes")
    if not codes:
        return True
    return finding.code in {c.strip() for c in codes.split(",")}


class _ScopeVisitor(ast.NodeVisitor):
    """Collects findings that need scope awareness (F401/F811/F841)."""

    def __init__(self, path: str, is_init: bool) -> None:
        self.path = path
        self.is_init = is_init
        self.findings: list = []
        # module-level import bindings: name -> (lineno, qualname-ish)
        self.imports: dict = {}
        self.used_names: set = set()
        self.module_dunder_all: set = set()

    # ---- imports
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.imports[name] = node.lineno
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.imports[name] = node.lineno
        self.generic_visit(node)

    # ---- usage
    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used_names.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # `foo.bar` marks `foo` used via the Name child; nothing extra.
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # __all__ entries count as usage (re-export surface).
        for target in node.targets:
            if isinstance(target, ast.Name) and target.id == "__all__":
                for elt in getattr(node.value, "elts", []):
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        self.module_dunder_all.add(elt.value)
        self.generic_visit(node)

    # ---- function-level checks
    def _check_function(self, node) -> None:
        # B006 mutable defaults
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
            ):
                self.findings.append(
                    Finding(self.path, default.lineno, "B006",
                            "mutable default argument")
                )
        # F841 unused locals: single-target simple assigns in this scope
        assigned: dict = {}
        used: set = set()

        class LocalWalk(ast.NodeVisitor):
            """Assignments from THIS scope only; usage from everywhere
            below it (nested defs/lambdas may close over our locals)."""

            def __init__(self, top: bool = True) -> None:
                self.top = top

            def visit_FunctionDef(self, n):
                LocalWalk(top=False).generic_visit(n)  # usage only

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, n):
                LocalWalk(top=False).generic_visit(n)

            def visit_ClassDef(self, n):
                # Class-body assigns are attribute definitions, not
                # function locals; still collect usage inside.
                LocalWalk(top=False).generic_visit(n)

            def visit_Assign(self, n):
                if (
                    self.top
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                ):
                    name = n.targets[0].id
                    if not name.startswith("_"):
                        assigned.setdefault(name, n.targets[0].lineno)
                # Visit everything: Store-ctx Names are ignored by
                # visit_Name, and non-Name targets (subscripts, attrs)
                # contain Loads that must count as usage.
                for child in ast.iter_child_nodes(n):
                    self.visit(child)

            def visit_Name(self, n):
                if isinstance(n.ctx, (ast.Load, ast.Del)):
                    used.add(n.id)

            def generic_visit(self, n):
                for child in ast.iter_child_nodes(n):
                    self.visit(child)

        walker = LocalWalk()
        for stmt in node.body:
            walker.visit(stmt)
        for name, lineno in assigned.items():
            if name not in used:
                self.findings.append(
                    Finding(self.path, lineno, "F841",
                            f"local variable {name!r} assigned but never used")
                )

    def visit_FunctionDef(self, node) -> None:
        self._check_function(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # ---- other checks
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.findings.append(
                Finding(self.path, node.lineno, "E722", "bare except"))
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.findings.append(
                Finding(self.path, node.lineno, "F541",
                        "f-string without placeholders"))
        # A placeholder's format spec (`{x:.3f}`) is itself a JoinedStr
        # with no FormattedValue — visiting it would false-positive F541.
        for value in node.values:
            if isinstance(value, ast.FormattedValue):
                self.visit(value.value)
            else:
                self.visit(value)

    # ---- redefinitions (same body scope, def/class only)
    def _check_redefs(self, body, where: str) -> None:
        seen: dict = {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                has_decorators = bool(stmt.decorator_list)
                if stmt.name in seen and not has_decorators and not seen[stmt.name]:
                    self.findings.append(
                        Finding(self.path, stmt.lineno, "F811",
                                f"redefinition of {stmt.name!r} ({where})"))
                seen[stmt.name] = has_decorators  # properties/overloads ok
            if isinstance(stmt, ast.ClassDef):
                self._check_redefs(stmt.body, f"class {stmt.name}")

    def finish(self, tree: ast.Module) -> None:
        self._check_redefs(tree.body, "module")
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_redefs(node.body, f"def {node.name}")
        if not self.is_init:
            for name, lineno in self.imports.items():
                if name in self.used_names or name in self.module_dunder_all:
                    continue
                if name == "annotations":  # from __future__
                    continue
                self.findings.append(
                    Finding(self.path, lineno, "F401",
                            f"{name!r} imported but unused"))


def lint_file(path: str) -> list:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "E999", f"syntax error: {e.msg}")]
    visitor = _ScopeVisitor(path, os.path.basename(path) == "__init__.py")
    visitor.visit(tree)
    visitor.finish(tree)
    for i, line in enumerate(lines, 1):
        m = MARKER_RE.search(line)
        if m:
            visitor.findings.append(
                Finding(path, i, "T100", f"{m.group(1)} marker"))
    return [f for f in visitor.findings if not _suppressed(lines, f)]


def iter_py(targets) -> list:
    out = []
    for target in targets:
        if os.path.isfile(target):
            out.append(target)
            continue
        for root, dirs, files in os.walk(target):
            dirs[:] = [d for d in dirs if d not in ("__pycache__", ".git")]
            out.extend(os.path.join(root, f) for f in files if f.endswith(".py"))
    return sorted(out)


def main(argv=None) -> int:
    targets = (argv or sys.argv[1:]) or DEFAULT_TARGETS
    findings = []
    n_files = 0
    for path in iter_py(targets):
        n_files += 1
        findings.extend(lint_file(path))
    for f in findings:
        print(f)
    print(f"lint: {n_files} files, {len(findings)} findings", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
