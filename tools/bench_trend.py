"""Trend-diff the committed BENCH_*.json artifacts against a baseline.

Every benchmark in this repo commits its report as a ``BENCH_*.json``
whose numeric fields are deterministic at the pinned seed (wall-clock
measurements are reduced to booleans before they reach the file). That
makes the git history of each artifact a longitudinal record: a p50 that
drifts up across commits is a perf regression landing in slow motion,
a ``*_within_budget`` flipping false is one landing all at once.

Default mode diffs the working tree against the previous commit
(``git show HEAD^:BENCH_x.json``); ``--old-dir/--new-dir`` diff two
directories instead (what the tests use — no git involved).

Classification per numeric leaf (reports are flattened to dotted paths):

- ``regressed``  — a boolean went truthy→falsy, or a magnitude moved
  against its direction hint past ``--tolerance`` (relative). Leaves
  whose last path segment suggests latency/loss (``*_ms``, ``*_seconds``,
  ``p50/p95/p99``, ``drifts``, ``violations``, ``failures``) regress
  upward; throughput-ish leaves (``*_per_s``, ``throughput``, ``ops``)
  regress downward; anything else is direction-neutral and only
  ``changed``.
- ``improved`` / ``changed`` / ``added`` / ``removed`` — informational.

Exit code is 0 unless inputs are malformed (or ``--fail-on-regression``
is set and something regressed): the gate's job is to make the trend
visible in CI logs, not to turn perf noise into a red build.

  make bench-trend
  python tools/bench_trend.py --fail-on-regression
  python tools/bench_trend.py --old-dir /tmp/base --new-dir .
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

REGRESS_UP = (
    "_ms", "_seconds", "_s", "p50", "p95", "p99", "drifts", "violations",
    "failures", "unsafe", "evictions", "misses", "dropped",
)
REGRESS_DOWN = ("_per_s", "throughput", "ops", "hits", "goodput", "hit_rate")

# Fields that IDENTIFY a bench row (which configuration was measured)
# rather than measure it. List items carrying any of these are keyed by
# them instead of by list position, so inserting a row (say, a new
# backend's A/B line) shifts nothing: every old row still diffs against
# the same configuration, and a p50/p95 drift is classified against its
# true baseline instead of a neighbour's. Measurement booleans
# (``byte_identical``, ``*_within_budget``) stay OUT of this set — they
# must keep flowing through classify() so a truthy→falsy flip reads
# ``regressed``, not ``removed`` + ``added``.
IDENTITY_KEYS = (
    "bench", "engine", "verdict_cache", "variant", "parallelism",
    "plan_mode", "backend", "copy", "mode", "kind",
    "nodes", "pods", "pending_pods", "pools", "churn", "watchers", "cpus",
)


def _item_key(item: object) -> str:
    """Identity key for one list element; "" = no identity (positional)."""
    if not isinstance(item, dict) or "bench" not in item:
        return ""
    return ",".join(
        f"{k}={item[k]}" for k in IDENTITY_KEYS if k in item
    )


def flatten(report: object, prefix: str = "") -> Dict[str, object]:
    """Collapse a nested report to ``{"a.b.c": leaf}``. List items that
    look like bench rows (dicts with a ``bench`` field) are keyed by
    their identity fields; anything else indexes by position. Only
    scalar leaves are kept (strings included, compared by equality
    only)."""
    out: Dict[str, object] = {}
    if isinstance(report, dict):
        for key in sorted(report):
            out.update(flatten(report[key], f"{prefix}{key}."))
    elif isinstance(report, list):
        seen: Dict[str, int] = {}
        for i, item in enumerate(report):
            key = _item_key(item)
            if key:
                # Repeated identical configs (re-run rows) stay distinct
                # and ordered via an occurrence suffix.
                n = seen.get(key, 0)
                seen[key] = n + 1
                if n:
                    key = f"{key}#{n}"
                out.update(flatten(item, f"{prefix}{key}."))
            else:
                out.update(flatten(item, f"{prefix}{i}."))
    else:
        out[prefix[:-1]] = report
    return out


def direction(path: str) -> int:
    """+1 = bigger is worse, -1 = smaller is worse, 0 = neutral."""
    leaf = path.rsplit(".", 1)[-1]
    if any(leaf.endswith(h) or leaf == h.strip("_") for h in REGRESS_UP):
        return 1
    if any(h in leaf for h in REGRESS_DOWN):
        return -1
    return 0


def classify(path: str, old: object, new: object, tolerance: float) -> Optional[str]:
    """One leaf's verdict: 'regressed' / 'improved' / 'changed' / None
    (within tolerance or equal)."""
    if isinstance(old, bool) or isinstance(new, bool):
        if bool(old) == bool(new):
            return None
        return "regressed" if bool(old) and not bool(new) else "improved"
    if isinstance(old, (int, float)) and isinstance(new, (int, float)):
        if old == new:
            return None
        base = max(abs(old), 1e-12)
        rel = (new - old) / base
        if abs(rel) <= tolerance:
            return None
        sign = direction(path)
        if sign == 0:
            return "changed"
        worse = rel > 0 if sign > 0 else rel < 0
        return "regressed" if worse else "improved"
    return None if old == new else "changed"


def diff_reports(
    old: dict, new: dict, tolerance: float
) -> List[Tuple[str, str, object, object]]:
    """(verdict, path, old, new) rows, regressions first."""
    flat_old, flat_new = flatten(old), flatten(new)
    rows: List[Tuple[str, str, object, object]] = []
    for path in sorted(set(flat_old) | set(flat_new)):
        if path not in flat_old:
            rows.append(("added", path, None, flat_new[path]))
        elif path not in flat_new:
            rows.append(("removed", path, flat_old[path], None))
        else:
            verdict = classify(path, flat_old[path], flat_new[path], tolerance)
            if verdict is not None:
                rows.append((verdict, path, flat_old[path], flat_new[path]))
    order = {"regressed": 0, "improved": 1, "changed": 2, "added": 3, "removed": 4}
    rows.sort(key=lambda r: (order[r[0]], r[1]))
    return rows


def _parse(text: str) -> object:
    """One JSON document, or JSONL (bench_planner appends line-records)
    parsed to the list of its documents."""
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        return [json.loads(line) for line in text.splitlines() if line.strip()]


def _git_show(ref: str, name: str, repo: str) -> Optional[object]:
    proc = subprocess.run(
        ["git", "show", f"{ref}:{name}"],
        cwd=repo,
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        return None  # new artifact: no baseline at this ref
    return _parse(proc.stdout)


def _load(path: str) -> Optional[object]:
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return _parse(fh.read())


def render(name: str, rows: List[Tuple[str, str, object, object]]) -> str:
    if not rows:
        return f"{name}: unchanged"
    lines = [f"{name}:"]
    for verdict, path, old, new in rows:
        lines.append(f"  {verdict:9s} {path}: {old!r} -> {new!r}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Diff committed BENCH_*.json artifacts against a baseline"
    )
    parser.add_argument(
        "--ref", default="HEAD^", help="git baseline ref (default: HEAD^)"
    )
    parser.add_argument(
        "--old-dir", default="", help="baseline directory instead of git"
    )
    parser.add_argument(
        "--new-dir", default="", help="candidate directory (default: repo root)"
    )
    parser.add_argument("--tolerance", type=float, default=0.10)
    parser.add_argument("--fail-on-regression", action="store_true")
    args = parser.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    new_dir = args.new_dir or repo
    names = sorted(
        os.path.basename(p) for p in glob.glob(os.path.join(new_dir, "BENCH_*.json"))
    )
    if not names:
        print(f"bench-trend: no BENCH_*.json found in {new_dir}", file=sys.stderr)
        return 1

    regressions = 0
    for name in names:
        new = _load(os.path.join(new_dir, name))
        if new is None:
            continue
        if args.old_dir:
            old = _load(os.path.join(args.old_dir, name))
        else:
            old = _git_show(args.ref, name, repo)
        if old is None:
            print(f"{name}: no baseline (new artifact)")
            continue
        rows = diff_reports(old, new, args.tolerance)
        print(render(name, rows))
        regressions += sum(1 for r in rows if r[0] == "regressed")

    if regressions:
        print(f"bench-trend: {regressions} regression(s) past tolerance")
        if args.fail_on_regression:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
