{{/* Common labels */}}
{{- define "nos-tpu.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{/* Image reference for a component: (dict "root" . "component" "operator") */}}
{{- define "nos-tpu.image" -}}
{{- $tag := .root.Values.image.tag | default .root.Chart.AppVersion -}}
{{ .root.Values.image.registry }}/nos-tpu-{{ .component }}:{{ $tag }}
{{- end }}

{{/* Service account name */}}
{{- define "nos-tpu.serviceAccountName" -}}
{{ .Release.Name }}-nos-tpu
{{- end }}

{{/* Config stanzas shared by every component: store backend + leader
     election. Rendered INTO each component's yaml (the Python entrypoints
     read top-level `store:` and `leaderElection:` keys —
     nos_tpu/cmd/_component.py). */}}
{{- define "nos-tpu.commonConfig" -}}
store:
  type: {{ .Values.store.type }}
leaderElection:
  enabled: {{ .Values.leaderElection.enabled }}
  namespace: {{ .Release.Namespace }}
  leaseDurationSeconds: {{ .Values.leaderElection.leaseDurationSeconds }}
  renewPeriodSeconds: {{ .Values.leaderElection.renewPeriodSeconds }}
{{- end }}

{{/* Metrics protection (reference helm-charts/nos/values.yaml:40-55):
     a kube-rbac-proxy sidecar fronting the health/metrics port. The
     component binds loopback; only the proxy's authenticated 8443 is
     exposed. Sidecar-free alternative: metricsAuth.secretName mounts a
     bearer token the in-process server enforces on /metrics. */}}
{{- define "nos-tpu.kubeRbacProxySidecar" -}}
{{- if .Values.kubeRbacProxy.enabled }}
- name: kube-rbac-proxy
  image: "{{ .Values.kubeRbacProxy.image.repository }}:{{ .Values.kubeRbacProxy.image.tag }}"
  imagePullPolicy: {{ .Values.kubeRbacProxy.image.pullPolicy }}
  args:
    - --secure-listen-address=0.0.0.0:8443
    - --upstream=http://127.0.0.1:8082/
    - --logtostderr=true
    {{- if gt (int .Values.kubeRbacProxy.logLevel) 0 }}
    - --v={{ .Values.kubeRbacProxy.logLevel }}
    {{- end }}
  ports:
    - containerPort: 8443
      name: https-metrics
      protocol: TCP
  resources:
    {{- toYaml .Values.kubeRbacProxy.resources | nindent 4 }}
{{- end }}
{{- end }}

{{/* Manager stanza. With the rbac proxy, /metrics moves to a
     loopback-only listener (8082) the sidecar fronts while healthz/readyz
     stay on pod-IP:8081 for kubelet probes; with metricsAuth, the
     in-process server enforces the mounted bearer token per scrape. */}}
{{- define "nos-tpu.managerConfig" -}}
manager:
  healthProbePort: 8081
{{- if .Values.kubeRbacProxy.enabled }}
  metricsLoopbackPort: 8082
{{- end }}
{{- if .Values.metricsAuth.secretName }}
  metricsAuthTokenFile: /var/run/nos-tpu-metrics-auth/token
{{- end }}
{{- end }}

{{/* Volume + mount for the metricsAuth token secret. */}}
{{- define "nos-tpu.metricsAuthVolume" -}}
{{- if .Values.metricsAuth.secretName }}
- name: metrics-auth
  secret:
    secretName: {{ .Values.metricsAuth.secretName }}
{{- end }}
{{- end }}
{{- define "nos-tpu.metricsAuthMount" -}}
{{- if .Values.metricsAuth.secretName }}
- name: metrics-auth
  mountPath: /var/run/nos-tpu-metrics-auth
  readOnly: true
{{- end }}
{{- end }}
