{{/* Common labels */}}
{{- define "nos-tpu.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{/* Image reference for a component: (dict "root" . "component" "operator") */}}
{{- define "nos-tpu.image" -}}
{{- $tag := .root.Values.image.tag | default .root.Chart.AppVersion -}}
{{ .root.Values.image.registry }}/nos-tpu-{{ .component }}:{{ $tag }}
{{- end }}

{{/* Service account name */}}
{{- define "nos-tpu.serviceAccountName" -}}
{{ .Release.Name }}-nos-tpu
{{- end }}

{{/* Config stanzas shared by every component: store backend + leader
     election. Rendered INTO each component's yaml (the Python entrypoints
     read top-level `store:` and `leaderElection:` keys —
     nos_tpu/cmd/_component.py). */}}
{{- define "nos-tpu.commonConfig" -}}
store:
  type: {{ .Values.store.type }}
leaderElection:
  enabled: {{ .Values.leaderElection.enabled }}
  namespace: {{ .Release.Namespace }}
  leaseDurationSeconds: {{ .Values.leaderElection.leaseDurationSeconds }}
  renewPeriodSeconds: {{ .Values.leaderElection.renewPeriodSeconds }}
{{- end }}
