{{/* Common labels */}}
{{- define "nos-tpu.labels" -}}
app.kubernetes.io/name: {{ .Chart.Name }}
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/version: {{ .Chart.AppVersion | quote }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end }}

{{/* Image reference for a component: (dict "root" . "component" "operator") */}}
{{- define "nos-tpu.image" -}}
{{- $tag := .root.Values.image.tag | default .root.Chart.AppVersion -}}
{{ .root.Values.image.registry }}/nos-tpu-{{ .component }}:{{ $tag }}
{{- end }}

{{/* Service account name */}}
{{- define "nos-tpu.serviceAccountName" -}}
{{ .Release.Name }}-nos-tpu
{{- end }}
