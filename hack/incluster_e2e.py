"""Hardware-free in-cluster dry run: the helm chart's component processes
against a real HTTP apiserver.

The reference validates its chart on a 3-node kind cluster
(hack/kind/cluster.yaml). This image has no container runtime, so the same
path is proven with the pieces we can run for real:

- the **stub apiserver** (`nos_tpu.sim.apiserver`) serves the apiserver
  wire subset over real loopback HTTP;
- each component runs as its OWN subprocess via the exact entry points the
  Dockerfiles use (`python -m nos_tpu <component> --config ...`), with a
  config mirroring the chart's ConfigMaps — `store.type: kubeconfig`
  exercises the same `KubeApiClient`/`KubeApiStore` code path an
  in-cluster service account does, just with file credentials;
- a sim kubelet (the chart's `deviceBackend: sim` stand-in for real node
  agents) admits bound pods and flips them Running.

Flow: boot apiserver -> write kubeconfig + per-component YAML -> spawn
operator, partitioner, scheduler, one tpuagent per tpu-mode node and a
sharingagent for the sharing-mode node -> create 2 TPU nodes + 1 sharing
node + an ElasticQuota -> submit chip pods AND an HBM-fraction pod
(schedulerName opt-in) -> assert every pod goes Running over the wire
(the shared pod via the ConfigMap + label-flip actuation style), health
endpoints answer, and all children exit 0 on SIGTERM.

Run: `make incluster-e2e` (or PYTHONPATH=. python hack/incluster_e2e.py).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from nos_tpu.api.v1alpha1 import constants, labels  # noqa: E402
from nos_tpu.api.v1alpha1.elasticquota import (  # noqa: E402
    ElasticQuota,
    ElasticQuotaSpec,
)
from nos_tpu.kube.apiclient import ClusterCredentials, KubeApiClient  # noqa: E402
from nos_tpu.kube.apistore import KubeApiStore  # noqa: E402
from nos_tpu.kube.controller import Controller, Manager, Watch  # noqa: E402
from nos_tpu.kube.objects import (  # noqa: E402
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
)
from nos_tpu.sim.apiserver import StubApiServer  # noqa: E402
from nos_tpu.sim.kubelet import SimKubelet  # noqa: E402

NODES = ("kind-worker", "kind-worker2")
SHARING_NODE = "kind-worker3"
# Hybrid: slice carving AND HBM sharing on ONE node — both agents run.
HYBRID_NODE = "kind-worker4"
HEALTH_PORTS = {"operator": 18181, "partitioner": 18182, "scheduler": 18183,
                "tpuagent-kind-worker": 18184, "tpuagent-kind-worker2": 18185,
                "sharingagent-kind-worker3": 18186,
                "tpuagent-kind-worker4": 18187,
                "sharingagent-kind-worker4": 18188}


def write_configs(tmp: str, server_url: str) -> dict:
    """Per-component YAML mirroring helm-charts/nos-tpu/templates/*
    configmaps, store switched to the apiserver (chart `store.type`)."""
    kubeconfig = os.path.join(tmp, "kubeconfig")
    with open(kubeconfig, "w") as f:
        f.write(f"""apiVersion: v1
kind: Config
current-context: e2e
clusters:
  - name: e2e
    cluster: {{server: "{server_url}"}}
users:
  - name: e2e
    user: {{}}
contexts:
  - name: e2e
    context: {{cluster: e2e, user: e2e}}
""")
    store_block = f"store:\n  type: kubeconfig\n  kubeconfig: {kubeconfig}\n"
    configs = {}

    def emit(name: str, body: str, port: int) -> None:
        path = os.path.join(tmp, f"{name}.yaml")
        with open(path, "w") as f:
            f.write(body + store_block + f"manager:\n  healthProbePort: {port}\n")
        configs[name] = path

    emit("operator", "tpuChipMemoryGB: 16\nwebhook:\n  enabled: false\n",
         HEALTH_PORTS["operator"])
    emit("partitioner",
         "partitioner:\n  batchWindowTimeoutSeconds: 0.3\n"
         "  batchWindowIdleSeconds: 0.05\n  agingChipsPerSecond: 1.0\n",
         HEALTH_PORTS["partitioner"])
    emit("scheduler",
         "scheduler:\n  retrySeconds: 0.1\n  gangWaitTimeoutSeconds: 10\n"
         f"  schedulerName: {constants.SCHEDULER_NAME}\n",
         HEALTH_PORTS["scheduler"])
    for node in NODES:
        emit(f"tpuagent-{node}",
             "agent:\n  reportConfigIntervalSeconds: 0.2\ndeviceBackend: sim\n",
             HEALTH_PORTS[f"tpuagent-{node}"])
    for name in (f"sharingagent-{SHARING_NODE}", f"sharingagent-{HYBRID_NODE}"):
        emit(name, "agent:\n  reportConfigIntervalSeconds: 0.2\n",
             HEALTH_PORTS[name])
    emit(f"tpuagent-{HYBRID_NODE}",
         "agent:\n  reportConfigIntervalSeconds: 0.2\ndeviceBackend: sim\n",
         HEALTH_PORTS[f"tpuagent-{HYBRID_NODE}"])
    return configs


def spawn(component: str, config_path: str, node: str = "") -> subprocess.Popen:
    env = dict(os.environ, PYTHONPATH=REPO)
    if node:
        env["NODE_NAME"] = node
    return subprocess.Popen(
        [sys.executable, "-m", "nos_tpu", component, "--config", config_path],
        env=env, cwd=REPO,
    )


def tpu_node(name: str, partitioning: str = "tpu") -> Node:
    alloc = {constants.RESOURCE_TPU: 8, "cpu": 64, "memory": 256}
    return Node(
        metadata=ObjectMeta(name=name, labels={
            labels.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice",
            labels.GKE_TPU_TOPOLOGY_LABEL: "2x4",
            labels.PARTITIONING_LABEL: partitioning,
        }),
        status=NodeStatus(capacity=dict(alloc), allocatable=dict(alloc)),
    )


def chip_pod(name: str, chips: int, ns: str = "ml") -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            containers=[Container(requests={constants.RESOURCE_TPU: chips})],
            scheduler_name=constants.SCHEDULER_NAME,
        ),
    )


def shared_pod(name: str, ns: str = "ml") -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            containers=[
                Container(requests={constants.tpu_shared_resource(8): 1})
            ],
            scheduler_name=constants.SCHEDULER_NAME,
        ),
    )


def wait_for(predicate, timeout: float = 60.0, interval: float = 0.2) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def healthz_ok(port: int) -> bool:
    import http.client

    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=2)
        conn.request("GET", "/healthz")
        return conn.getresponse().status == 200
    except OSError:
        return False


def main() -> int:
    procs: dict = {}
    with StubApiServer() as api, tempfile.TemporaryDirectory(
        prefix="nos-e2e-"
    ) as tmp:
        print(f"[e2e] apiserver at {api.url}")
        configs = write_configs(tmp, api.url)

        # Harness-side store: seeding objects + the sim kubelet, over the
        # same wire protocol the components use.
        store = KubeApiStore(
            KubeApiClient(ClusterCredentials(server=api.url), timeout=5.0)
        )
        store.start(sync_timeout_s=15.0)
        kubelet = SimKubelet(store)
        mgr = Manager(store)
        mgr.add(Controller("sim-kubelet", store, kubelet.reconcile,
                           [Watch(kind="Pod")]))
        # Sharing-mode node-side stand-in: the sim device plugin reads the
        # plugin ConfigMap when a node's config label flips and
        # re-advertises tpu-mem resources (what the real TPU device plugin
        # daemonset does; the chart's second actuation style).
        from nos_tpu.api.v1alpha1.labels import TPU_DEVICE_PLUGIN_CONFIG_LABEL
        from nos_tpu.device.sharing import SimSharedDevicePlugin
        from nos_tpu.kube.controller import Request

        shared_plugin = SimSharedDevicePlugin(store)

        def configmap_to_labeled_nodes(event):
            return [
                Request(name=n.metadata.name)
                for n in store.list("Node")
                if TPU_DEVICE_PLUGIN_CONFIG_LABEL in n.metadata.labels
            ]

        mgr.add(Controller(
            "sim-shared-device-plugin", store, shared_plugin.reconcile,
            [
                Watch(
                    kind="Node",
                    predicate=lambda e: e.type != "DELETED"
                    and TPU_DEVICE_PLUGIN_CONFIG_LABEL
                    in e.object.metadata.labels,
                ),
                Watch(kind="ConfigMap", mapper=configmap_to_labeled_nodes),
            ],
        ))
        mgr.start()

        try:
            for name in ("operator", "partitioner", "scheduler"):
                procs[name] = spawn(name, configs[name])
            for node in NODES:
                procs[f"tpuagent-{node}"] = spawn(
                    "tpuagent", configs[f"tpuagent-{node}"], node=node
                )
            procs[f"sharingagent-{SHARING_NODE}"] = spawn(
                "sharingagent", configs[f"sharingagent-{SHARING_NODE}"],
                node=SHARING_NODE,
            )
            # Hybrid node: BOTH daemons, like the chart's daemonsets would
            # co-schedule on a hybrid-labeled node.
            procs[f"tpuagent-{HYBRID_NODE}"] = spawn(
                "tpuagent", configs[f"tpuagent-{HYBRID_NODE}"], node=HYBRID_NODE
            )
            procs[f"sharingagent-{HYBRID_NODE}"] = spawn(
                "sharingagent", configs[f"sharingagent-{HYBRID_NODE}"],
                node=HYBRID_NODE,
            )
            print(f"[e2e] spawned {len(procs)} component processes")

            for node in NODES:
                store.create(tpu_node(node))
            store.create(tpu_node(SHARING_NODE, partitioning="sharing"))
            hybrid = tpu_node(HYBRID_NODE, partitioning="hybrid")
            hybrid.metadata.labels[labels.SHARED_CHIPS_LABEL] = "4"
            hybrid.metadata.labels["e2e/pin"] = "hybrid"
            store.create(hybrid)
            # min == the full chip inventory (2 tpu nodes + the hybrid
            # node's carvable half): with a single quota there is no other
            # namespace to borrow unused guarantees from, so demand beyond
            # min would (correctly) be rejected by CapacityScheduling.
            store.create(ElasticQuota(
                metadata=ObjectMeta(name="eq-ml", namespace="ml"),
                spec=ElasticQuotaSpec(
                    min={constants.RESOURCE_TPU_CHIPS: 24},
                    max={constants.RESOURCE_TPU_CHIPS: 24},
                ),
            ))

            # Mixed shapes: a board, a half board, two singles -> forces a
            # real carve on both nodes. Plus an HBM-fraction pod that must
            # ride the SHARING actuation style (ConfigMap + label flip).
            # The hybrid node's carvable half takes hyb-slice (its 4
            # non-shared chips = one 2x2), its shared half hyb-infer; both
            # are PINNED there via nodeSelector and submitted FIRST — the
            # unpinned pods below can legally land on the hybrid node too
            # (a sharing/hybrid node's free capacity serves anyone), and
            # the point is proving ONE node serves both actuation styles.
            for name in ("hyb-slice", "hyb-infer"):
                pod = chip_pod(name, 4) if name == "hyb-slice" else shared_pod(name)
                pod.spec.node_selector = {"e2e/pin": "hybrid"}
                store.create(pod)

            def hyb_running() -> bool:
                return all(
                    store.get("Pod", n, "ml").status.phase == PodPhase.RUNNING
                    for n in ("hyb-slice", "hyb-infer")
                )

            if not wait_for(hyb_running, timeout=60.0):
                for n in ("hyb-slice", "hyb-infer"):
                    p = store.get("Pod", n, "ml")
                    print(f"[e2e]   {n}: {p.status.phase} "
                          f"{[c.message for c in p.status.conditions]}")
                print("[e2e] FAIL: hybrid-pinned pods did not run")
                return 1
            print("[e2e] hybrid node served a slice AND an HBM fraction")

            pods = [("board", 8), ("half", 4), ("one-a", 1), ("one-b", 1),
                    ("shared-infer", 0)]
            for name, chips in pods:
                store.create(
                    shared_pod(name) if chips == 0 else chip_pod(name, chips)
                )

            def all_running() -> bool:
                for name, _ in pods:
                    pod = store.try_get("Pod", name, "ml")
                    if pod is None or pod.status.phase != PodPhase.RUNNING:
                        return False
                return True

            ok = wait_for(all_running, timeout=90.0)
            for name, _ in pods:
                pod = store.try_get("Pod", name, "ml")
                phase = pod.status.phase if pod else "GONE"
                node = pod.spec.node_name if pod else ""
                print(f"[e2e]   pod {name}: {phase} on {node!r}")
            if not ok:
                for node in NODES + (SHARING_NODE, HYBRID_NODE):
                    n = store.try_get("Node", node)
                    print(f"[e2e]   node {node} allocatable: "
                          f"{n.status.allocatable if n else None}")
                    if n is not None and node in (SHARING_NODE, HYBRID_NODE):
                        print(f"[e2e]     labels: {n.metadata.labels}")
                        print(f"[e2e]     annotations: {n.metadata.annotations}")
                for name, _ in pods:
                    pod = store.try_get("Pod", name, "ml")
                    if pod is not None:
                        conds = [
                            (c.type, c.status, c.message)
                            for c in pod.status.conditions
                        ]
                        print(f"[e2e]   pod {name} conditions: {conds}")
                print("[e2e] FAIL: pods did not all reach Running")
                return 1
            print("[e2e] all pods Running over the wire")
            shared = store.get("Pod", "shared-infer", "ml")
            if shared.spec.node_name not in (SHARING_NODE, HYBRID_NODE):
                print(f"[e2e] FAIL: shared pod on {shared.spec.node_name!r}, "
                      "expected a sharing-capable node")
                return 1

            from nos_tpu.api.v1alpha1.labels import (
                TPU_DEVICE_PLUGIN_CONFIG_LABEL as _CFG_LABEL,
            )

            node3 = store.get("Node", SHARING_NODE)
            if _CFG_LABEL not in node3.metadata.labels:
                print("[e2e] FAIL: sharing node never got its config label")
                return 1
            print("[e2e] sharing-mode actuation proven (ConfigMap + label flip)")

            bad_health = [n for n, p in HEALTH_PORTS.items() if not healthz_ok(p)]
            if bad_health:
                print(f"[e2e] FAIL: healthz unreachable for {bad_health}")
                return 1
            print("[e2e] all component health endpoints answering")

            crashed = {n: p.poll() for n, p in procs.items() if p.poll() is not None}
            if crashed:
                print(f"[e2e] FAIL: components exited early: {crashed}")
                return 1
        finally:
            for proc in procs.values():
                if proc.poll() is None:
                    proc.send_signal(signal.SIGTERM)
            deadline = time.monotonic() + 15
            for proc in procs.values():
                try:
                    proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    proc.kill()
            mgr.stop()
            store.stop()

        rcs = {name: proc.returncode for name, proc in procs.items()}
        print(f"[e2e] component exit codes: {rcs}")
        if any(rc not in (0, -signal.SIGTERM) for rc in rcs.values()):
            print("[e2e] FAIL: non-clean component exits")
            return 1
        print("[e2e] PASS: in-cluster path proven end-to-end")
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
